"""Random sampling ops (ref: python/paddle/tensor/random.py).

Eager convenience front over jax.random using the process-global stream.
Inside jit-traced code prefer explicit keys (`paddle_tpu.framework.random`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import dtype as dtype_mod
from ..framework import random as random_mod


def _dt(dtype):
    d = dtype_mod.convert_dtype(dtype)
    return d if d is not None else dtype_mod.get_default_dtype()


def rand(shape, dtype=None):
    return jax.random.uniform(random_mod.split_key(), tuple(shape), dtype=_dt(dtype))


uniform_random = rand


def randn(shape, dtype=None):
    return jax.random.normal(random_mod.split_key(), tuple(shape), dtype=_dt(dtype))


def normal(mean=0.0, std=1.0, shape=None):
    if shape is None:
        shape = jnp.shape(mean) if hasattr(mean, 'shape') else ()
    return mean + std * jax.random.normal(
        random_mod.split_key(), tuple(shape), dtype=dtype_mod.get_default_dtype()
    )


def uniform(shape, dtype=None, min=-1.0, max=1.0):
    return jax.random.uniform(
        random_mod.split_key(), tuple(shape), dtype=_dt(dtype), minval=min, maxval=max
    )


def randint(low=0, high=None, shape=(1,), dtype='int64'):
    if high is None:
        low, high = 0, low
    return jax.random.randint(
        random_mod.split_key(), tuple(shape), low, high, dtype=dtype_mod.convert_dtype(dtype)
    )


def randint_like(x, low=0, high=None, dtype=None):
    return randint(low, high, x.shape, dtype or x.dtype)


def randperm(n, dtype='int64'):
    return jax.random.permutation(random_mod.split_key(), n).astype(
        dtype_mod.convert_dtype(dtype)
    )


def shuffle(x, axis=0):
    return jax.random.permutation(random_mod.split_key(), x, axis=axis)


def multinomial(x, num_samples=1, replacement=False):
    k = random_mod.split_key()
    logits = jnp.log(jnp.clip(x, 1e-30, None))
    if replacement:
        return jax.random.categorical(k, logits, shape=(*x.shape[:-1], num_samples))
    # Gumbel top-k trick for sampling without replacement
    g = jax.random.gumbel(k, x.shape)
    _, idx = jax.lax.top_k(logits + g, num_samples)
    return idx


def bernoulli(x):
    return jax.random.bernoulli(random_mod.split_key(), x).astype(
        dtype_mod.get_default_dtype()
    )


def poisson(x):
    return jax.random.poisson(random_mod.split_key(), x).astype(
        dtype_mod.get_default_dtype()
    )


def exponential_(x, lam=1.0):
    return jax.random.exponential(random_mod.split_key(), x.shape, dtype=x.dtype) / lam


def standard_normal(shape, dtype=None):
    return randn(shape, dtype)


def standard_gamma(alpha, shape=None):
    return jax.random.gamma(random_mod.split_key(), alpha, shape=shape)


def binomial(count, prob):
    """ref: tensor/random.py::binomial — sample Binomial(count, prob)
    elementwise."""
    count = jnp.asarray(count)
    prob = jnp.asarray(prob, jnp.float32)
    key = random_mod.split_key()
    # int64 in the reference; int32 here (x64 is off by default in jax)
    return jax.random.binomial(key, count.astype(jnp.float32),
                               prob).astype(jnp.int32)


def log_normal(mean=1.0, std=2.0, shape=None):
    """ref: tensor/random.py::log_normal (module form)."""
    if shape is None:
        shape = jnp.broadcast_shapes(jnp.shape(mean), jnp.shape(std))
    key = random_mod.split_key()
    return jnp.exp(jax.random.normal(key, tuple(shape)) * std + mean)


def log_normal_(x, mean=1.0, std=2.0):
    """In-place-style variant: fresh samples with x's shape/dtype."""
    return log_normal(mean, std, jnp.asarray(x).shape).astype(x.dtype)


def cauchy_(x, loc=0, scale=1):
    """ref: Tensor.cauchy_ — fill with Cauchy(loc, scale) samples."""
    x = jnp.asarray(x)
    key = random_mod.split_key()
    return (loc + scale * jax.random.cauchy(key, x.shape)).astype(x.dtype)


def geometric_(x, probs):
    """ref: Tensor.geometric_ — fill with Geometric(probs) samples
    (number of trials to first success, support {1, 2, ...})."""
    x = jnp.asarray(x)
    key = random_mod.split_key()
    return jax.random.geometric(key, probs, x.shape).astype(x.dtype)


def uniform_(x, min=-1.0, max=1.0, seed=0):
    """ref: Tensor.uniform_ — fill with U(min, max) samples of x's
    shape/dtype (seed=0: draw from the global generator)."""
    x = jnp.asarray(x)
    key = jax.random.PRNGKey(seed) if seed else random_mod.split_key()
    return jax.random.uniform(
        key, x.shape, minval=min, maxval=max).astype(x.dtype)


def normal_(x, mean=0.0, std=1.0):
    """ref: Tensor.normal_ — fill with N(mean, std) samples."""
    x = jnp.asarray(x)
    key = random_mod.split_key()
    return (mean + std * jax.random.normal(key, x.shape)).astype(x.dtype)


def bernoulli_(x, p=0.5):
    """ref: Tensor.bernoulli_ — fill with Bernoulli(p) samples (p is a
    scalar probability, unlike paddle.bernoulli(x) where x IS the
    probability tensor)."""
    x = jnp.asarray(x)
    key = random_mod.split_key()
    return jax.random.bernoulli(key, p, x.shape).astype(x.dtype)


def top_p_sampling(x, ps, threshold=None, topp_seed=None, seed=-1, k=0,
                   mode='truncated'):
    """Nucleus sampling over a [batch, vocab] probability tensor.

    ref: tensor/random.py::top_p_sampling (GPU kernel there; jnp here):
    keeps the smallest prefix of descending-sorted probs whose mass
    exceeds ``ps`` (per row), renormalises, samples one token. ``k > 0``
    additionally truncates to the top-k tokens; ``seed >= 0`` (or
    ``topp_seed``) makes the draw reproducible; ``mode='non-truncated'``
    skips the ``threshold`` floor (per the reference, threshold only
    applies in truncated mode). Returns (sampled probability, sampled
    index), both shaped [batch, 1].
    """
    x = jnp.asarray(x)
    ps = jnp.reshape(jnp.asarray(ps, dtype=x.dtype), (-1, 1))
    order = jnp.argsort(-x, axis=-1)
    sorted_p = jnp.take_along_axis(x, order, axis=-1)
    cum = jnp.cumsum(sorted_p, axis=-1)
    # keep token i if the mass strictly before it is < ps (always keeps
    # the top-1 token); optional threshold floor mirrors the reference.
    keep = (cum - sorted_p) < ps
    if k:
        keep = keep & (jnp.arange(x.shape[-1])[None, :] < k)
        keep = keep.at[:, 0].set(True)
    if threshold is not None and mode == 'truncated':
        keep = keep & (sorted_p >= jnp.reshape(
            jnp.asarray(threshold, dtype=x.dtype), (-1, 1)))
        keep = keep.at[:, 0].set(True)
    masked = jnp.where(keep, sorted_p, 0.0)
    probs = masked / jnp.sum(masked, axis=-1, keepdims=True)
    if topp_seed is not None:
        seed = topp_seed
    if seed is not None and not isinstance(seed, int):
        seed = int(jnp.reshape(seed, ()))  # tensor seed
    if seed is not None and seed >= 0:
        key = jax.random.PRNGKey(seed)
    else:
        key = random_mod.split_key()
    choice = jax.random.categorical(key, jnp.log(probs + 1e-30), axis=-1)
    choice = jnp.reshape(choice, (-1, 1))
    ids = jnp.take_along_axis(order, choice, axis=-1)
    vals = jnp.take_along_axis(x, ids, axis=-1)
    return vals, ids
