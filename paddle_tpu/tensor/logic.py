"""Logic/compare ops (ref: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp

equal = jnp.equal
not_equal = jnp.not_equal
greater_than = jnp.greater
greater_equal = jnp.greater_equal
less_than = jnp.less
less_equal = jnp.less_equal

logical_and = jnp.logical_and
logical_or = jnp.logical_or
logical_xor = jnp.logical_xor
logical_not = jnp.logical_not

bitwise_and = jnp.bitwise_and
bitwise_or = jnp.bitwise_or
bitwise_xor = jnp.bitwise_xor
bitwise_not = jnp.bitwise_not


def is_empty(x):
    return jnp.asarray(x.size == 0)


def is_tensor(x):
    import jax

    return isinstance(x, jax.Array)
