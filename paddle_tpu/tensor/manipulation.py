"""Shape/layout manipulation ops (ref: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def reshape(x, shape):
    # paddle semantics (ref tensor/manipulation.py::reshape): an entry of
    # 0 copies the input dim at the same index; -1 infers (jnp native)
    if not isinstance(shape, (list, tuple)):
        return jnp.reshape(x, shape)  # bare int / array shape
    shape = [x.shape[i] if s == 0 and i < x.ndim else s
             for i, s in enumerate(shape)]
    return jnp.reshape(x, shape)


def reshape_(x, shape):
    return reshape(x, shape)


def transpose(x, perm=None):
    return jnp.transpose(x, axes=perm)


def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


def swapaxes(x, axis0, axis1):
    return jnp.swapaxes(x, axis0, axis1)


def t(x, name=None):
    """ref: tensor/linalg.py::t — transpose for tensors of rank <= 2
    (rank 0/1 returned unchanged, like the reference)."""
    if x.ndim > 2:
        raise ValueError(
            f'paddle.t expects a tensor of rank <= 2, got shape {x.shape} '
            f'(use transpose/swapaxes for higher ranks)')
    return x if x.ndim < 2 else jnp.swapaxes(x, -2, -1)


def concat(x, axis=0):
    return jnp.concatenate(list(x), axis=axis)


def stack(x, axis=0):
    return jnp.stack(list(x), axis=axis)


def split(x, num_or_sections, axis=0):
    if isinstance(num_or_sections, int):
        return jnp.split(x, num_or_sections, axis=axis)
    sections = list(num_or_sections)
    total = x.shape[axis]
    if any(s in (-1, None) for s in sections):
        known = builtins_sum(s for s in sections if s not in (-1, None))
        sections = [total - known if s in (-1, None) else s for s in sections]
    idx = np.cumsum(sections)[:-1]
    return jnp.split(x, idx, axis=axis)


def builtins_sum(it):
    import builtins

    return builtins.sum(it)


def chunk(x, chunks, axis=0):
    return jnp.array_split(x, chunks, axis=axis)


def unbind(x, axis=0):
    return [jnp.squeeze(v, axis=axis) for v in jnp.split(x, x.shape[axis], axis=axis)]


def squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, (list, tuple)):
        axis = tuple(a for a in axis if x.shape[a] == 1)
        return jnp.squeeze(x, axis=axis) if axis else x
    if x.shape[axis] != 1:
        return x
    return jnp.squeeze(x, axis=axis)


def unsqueeze(x, axis):
    if isinstance(axis, (list, tuple)):
        for a in sorted(axis):
            x = jnp.expand_dims(x, a)
        return x
    return jnp.expand_dims(x, axis)


def expand(x, shape):
    shape = [x.shape[i - len(shape) + len(x.shape)] if s in (-1, None) else s
             for i, s in enumerate(shape)]
    return jnp.broadcast_to(x, shape)


def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


def broadcast_to(x, shape):
    return jnp.broadcast_to(x, shape)


def broadcast_tensors(inputs):
    return list(jnp.broadcast_arrays(*inputs))


def tile(x, repeat_times):
    return jnp.tile(x, repeat_times)


def flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return x.reshape(1)
    if start_axis < 0:
        start_axis += nd
    if stop_axis < 0:
        stop_axis += nd
    shape = (
        x.shape[:start_axis]
        + (int(np.prod(x.shape[start_axis : stop_axis + 1])),)
        + x.shape[stop_axis + 1 :]
    )
    return jnp.reshape(x, shape)


def flip(x, axis):
    return jnp.flip(x, axis=axis if not isinstance(axis, list) else tuple(axis))


def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=tuple(axes))


def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis if not isinstance(axis, list) else tuple(axis))


def gather(x, index, axis=0):
    index = index.reshape(-1)
    return jnp.take(x, index, axis=axis)


def gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


def take_along_axis(x, indices, axis, broadcast=True):
    return jnp.take_along_axis(x, indices, axis=axis)


def put_along_axis(x, indices, values, axis, reduce='assign'):
    if reduce == 'assign':
        return _scatter_along(x, indices, values, axis, 'set')
    if reduce == 'add':
        return _scatter_along(x, indices, values, axis, 'add')
    if reduce in ('mul', 'multiply'):
        return _scatter_along(x, indices, values, axis, 'mul')
    raise ValueError(reduce)


def _scatter_along(x, indices, values, axis, mode):
    values = jnp.broadcast_to(jnp.asarray(values, dtype=x.dtype), indices.shape)
    dims = []
    for i in range(x.ndim):
        if i == axis:
            dims.append(indices)
        else:
            shape = [1] * x.ndim
            shape[i] = x.shape[i] if i < axis else indices.shape[i]
            dims.append(jnp.broadcast_to(jnp.arange(indices.shape[i]).reshape(shape), indices.shape))
    idx = tuple(dims)
    at = x.at[idx]
    return getattr(at, {'set': 'set', 'add': 'add', 'mul': 'multiply'}[mode])(values)


def scatter(x, index, updates, overwrite=True):
    """ref: paddle.scatter — row-wise scatter on axis 0."""
    index = index.reshape(-1)
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd(index, updates, shape):
    zeros = jnp.zeros(shape, updates.dtype)
    return scatter_nd_add(zeros, index, updates)


def index_select(x, index, axis=0):
    return jnp.take(x, index.reshape(-1), axis=axis)


def index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


def index_add(x, index, axis, value):
    idx = [slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].add(value)


def index_put(x, indices, value, accumulate=False):
    if accumulate:
        return x.at[tuple(indices)].add(value)
    return x.at[tuple(indices)].set(value)


def masked_select(x, mask):
    return x[mask]


def masked_fill(x, mask, value):
    return jnp.where(mask, jnp.asarray(value, dtype=x.dtype), x)


def masked_scatter(x, mask, value):
    flat_mask = mask.reshape(-1)
    n = int(flat_mask.sum())
    out = x.reshape(-1).at[jnp.nonzero(flat_mask)[0]].set(value.reshape(-1)[:n])
    return out.reshape(x.shape)


def where(condition, x=None, y=None):
    if x is None and y is None:
        return jnp.nonzero(condition)
    return jnp.where(condition, x, y)


def nonzero(x, as_tuple=False):
    nz = jnp.nonzero(x)
    if as_tuple:
        return nz
    return jnp.stack(nz, axis=1)


def pad(x, pad, mode='constant', value=0.0, data_format=None):
    """ref: paddle.nn.functional.pad — pad is [before_last, after_last, ...]
    pairs from the LAST axis backwards when given flat ints (torch/paddle
    convention), or a full per-axis list."""
    if len(pad) == 2 * x.ndim:
        pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(x.ndim)]
    else:
        pairs = [(0, 0)] * (x.ndim - len(pad) // 2)
        it = list(zip(pad[0::2], pad[1::2]))
        pairs += [tuple(p) for p in reversed(it)]
    if mode == 'constant':
        return jnp.pad(x, pairs, mode='constant', constant_values=value)
    jmode = {'reflect': 'reflect', 'replicate': 'edge', 'circular': 'wrap'}[mode]
    return jnp.pad(x, pairs, mode=jmode)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None):
    return jnp.unique(
        x,
        return_index=return_index,
        return_inverse=return_inverse,
        return_counts=return_counts,
        axis=axis,
    )


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None):
    x_flat = x if axis is not None else x.reshape(-1)
    keep = jnp.concatenate([jnp.array([True]), x_flat[1:] != x_flat[:-1]])
    return x_flat[keep]


def sort(x, axis=-1, descending=False, stable=True):
    out = jnp.sort(x, axis=axis, stable=stable)
    return jnp.flip(out, axis=axis) if descending else out


def argsort(x, axis=-1, descending=False, stable=True):
    out = jnp.argsort(x, axis=axis, stable=stable)
    return jnp.flip(out, axis=axis) if descending else out


def argmax(x, axis=None, keepdim=False, dtype='int64'):
    return jnp.argmax(x, axis=axis, keepdims=keepdim).astype(dtype)


def argmin(x, axis=None, keepdim=False, dtype='int64'):
    return jnp.argmin(x, axis=axis, keepdims=keepdim).astype(dtype)


def topk(x, k, axis=-1, largest=True, sorted=True):
    if axis != -1 and axis != x.ndim - 1:
        xm = jnp.moveaxis(x, axis, -1)
        v, i = topk(xm, k, -1, largest, sorted)
        return jnp.moveaxis(v, -1, axis), jnp.moveaxis(i, -1, axis)
    if largest:
        v, i = jax.lax.top_k(x, k)
    else:
        v, i = jax.lax.top_k(-x, k)
        v = -v
    return v, i


def kthvalue(x, k, axis=-1, keepdim=False):
    v = jnp.sort(x, axis=axis)
    i = jnp.argsort(x, axis=axis)
    vk = jnp.take(v, k - 1, axis=axis)
    ik = jnp.take(i, k - 1, axis=axis)
    if keepdim:
        vk = jnp.expand_dims(vk, axis)
        ik = jnp.expand_dims(ik, axis)
    return vk, ik


def mode(x, axis=-1, keepdim=False):
    v = jnp.sort(x, axis=axis)
    # most frequent via run-length on sorted values (static-shape friendly)
    eq = v == jnp.roll(v, 1, axis=axis)
    runs = jnp.cumsum(eq, axis=axis)
    idx = jnp.argmax(runs, axis=axis, keepdims=True)
    out = jnp.take_along_axis(v, idx, axis=axis)
    if not keepdim:
        out = jnp.squeeze(out, axis=axis)
    return out, idx if keepdim else jnp.squeeze(idx, axis=axis)


def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = 'right' if right else 'left'
    out = jnp.searchsorted(sorted_sequence, values, side=side)
    return out.astype(jnp.int32) if out_int32 else out


def bucketize(x, sorted_sequence, out_int32=False, right=False):
    return searchsorted(sorted_sequence, x, out_int32, right)


def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


def as_strided(x, shape, stride, offset=0):
    # XLA has no strided views; emulate via gather for the common cases.
    idx = offset + np.sum(
        np.stack(np.meshgrid(*[np.arange(s) for s in shape], indexing='ij'), 0)
        * np.array(stride).reshape((-1,) + (1,) * len(shape)),
        axis=0,
    )
    return x.reshape(-1)[idx]


def view(x, shape_or_dtype):
    if isinstance(shape_or_dtype, (list, tuple)):
        return jnp.reshape(x, shape_or_dtype)
    # dtype reinterpret: use jax's original .view — the method itself is
    # rebound to this function, so calling x.view here would recurse
    from .methods import _ORIGINALS
    orig = _ORIGINALS.get('view')
    if orig is not None:
        return orig(x, shape_or_dtype)
    return x.view(shape_or_dtype)


def crop(x, shape=None, offsets=None):
    offsets = offsets or [0] * x.ndim
    shape = [x.shape[i] - offsets[i] if s in (-1, None) else s for i, s in enumerate(shape)]
    slices = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return x[slices]


def slice(x, axes, starts, ends):
    idx = [builtins_slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        idx[a] = builtins_slice(s, e)
    return x[tuple(idx)]


def builtins_slice(*a):
    import builtins

    return builtins.slice(*a)


def strided_slice(x, axes, starts, ends, strides):
    idx = [builtins_slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = builtins_slice(s, e, st)
    return x[tuple(idx)]


def tensordot(x, y, axes=2):
    return jnp.tensordot(x, y, axes=axes)


def cdist(x, y, p=2.0):
    diff = x[..., :, None, :] - y[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 1e-30)
    return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)


def dist(x, y, p=2.0):
    d = (x - y).reshape(-1)
    if p == float('inf'):
        return jnp.max(jnp.abs(d))
    if p == 0:
        return jnp.sum(d != 0).astype(x.dtype)
    return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def equal_all(x, y):
    return jnp.array_equal(x, y)


def cast(x, dtype):
    from ..framework import dtype as dtype_mod

    return x.astype(dtype_mod.convert_dtype(dtype))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    shard_size = index_num // nshards
    lo, hi = shard_id * shard_size, (shard_id + 1) * shard_size
    inside = (input >= lo) & (input < hi)
    return jnp.where(inside, input - lo, ignore_value)
