"""Math ops (ref: python/paddle/tensor/math.py, ops.py).

Thin Paddle-signature fronts over jnp — jnp *is* the TPU kernel library
here (every call lowers to XLA HLO and fuses)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

# elementwise binary
add = jnp.add
subtract = jnp.subtract
multiply = jnp.multiply
divide = jnp.divide
floor_divide = jnp.floor_divide
mod = remainder = jnp.remainder
pow = jnp.power
maximum = jnp.maximum
minimum = jnp.minimum
fmax = jnp.fmax
fmin = jnp.fmin
atan2 = jnp.arctan2
hypot = jnp.hypot
copysign = jnp.copysign
nextafter = jnp.nextafter
ldexp = jnp.ldexp
gcd = jnp.gcd
lcm = jnp.lcm
heaviside = jnp.heaviside


def divide_no_nan(x, y):
    return jnp.where(y == 0, jnp.zeros_like(x), x / jnp.where(y == 0, 1, y))


def multiplex(inputs, index):
    stacked = jnp.stack(inputs, axis=0)
    idx = index.reshape(-1).astype(jnp.int32)
    return stacked[idx, jnp.arange(stacked.shape[1])]


# elementwise unary
abs = jnp.abs
neg = negative = jnp.negative
exp = jnp.exp
expm1 = jnp.expm1
log = jnp.log
log2 = jnp.log2
log10 = jnp.log10
log1p = jnp.log1p
sqrt = jnp.sqrt
rsqrt = jax.lax.rsqrt
square = jnp.square
sign = jnp.sign
sin = jnp.sin
cos = jnp.cos
tan = jnp.tan
asin = arcsin = jnp.arcsin
acos = arccos = jnp.arccos
atan = arctan = jnp.arctan
sinh = jnp.sinh
cosh = jnp.cosh
tanh = jnp.tanh
asinh = jnp.arcsinh
acosh = jnp.arccosh
atanh = jnp.arctanh
ceil = jnp.ceil
floor = jnp.floor
round = jnp.round
trunc = jnp.trunc
frac = lambda x: x - jnp.trunc(x)
reciprocal = jnp.reciprocal
erf = jax.scipy.special.erf
erfinv = jax.scipy.special.erfinv
lgamma = jax.scipy.special.gammaln
digamma = jax.scipy.special.digamma
i0 = jnp.i0
isnan = jnp.isnan
isinf = jnp.isinf
isfinite = jnp.isfinite
deg2rad = jnp.deg2rad
rad2deg = jnp.rad2deg
angle = jnp.angle
conj = jnp.conj
real = jnp.real
imag = jnp.imag


def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1 - eps)
    return jnp.log(x / (1 - x))


def clip(x, min=None, max=None):
    return jnp.clip(x, min, max)


def lerp(x, y, weight):
    return x + weight * (y - x)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


# reductions
def _axis(axis):
    if isinstance(axis, list):
        return tuple(axis)
    return axis


def sum(x, axis=None, dtype=None, keepdim=False):
    return jnp.sum(x, axis=_axis(axis), dtype=dtype, keepdims=keepdim)


def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=_axis(axis), keepdims=keepdim)


def max(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


def min(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


def amax(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


def amin(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


def prod(x, axis=None, keepdim=False, dtype=None):
    return jnp.prod(x, axis=_axis(axis), dtype=dtype, keepdims=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


def median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=_axis(axis), keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False):
    return jnp.quantile(x, q, axis=_axis(axis), keepdims=keepdim)


def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=_axis(axis), keepdims=keepdim)


def nansum(x, axis=None, dtype=None, keepdim=False):
    return jnp.nansum(x, axis=_axis(axis), dtype=dtype, keepdims=keepdim)


def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=_axis(axis), keepdims=keepdim)


def all(x, axis=None, keepdim=False):
    return jnp.all(x, axis=_axis(axis), keepdims=keepdim)


def any(x, axis=None, keepdim=False):
    return jnp.any(x, axis=_axis(axis), keepdims=keepdim)


def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=_axis(axis), keepdims=keepdim)


# cumulative
def cumsum(x, axis=None, dtype=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.cumsum(x, axis=axis, dtype=dtype)


def cumprod(x, dim=None, dtype=None):
    if dim is None:
        x = x.reshape(-1)
        dim = 0
    return jnp.cumprod(x, axis=dim, dtype=dtype)


def cummax(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    vals = jax.lax.associative_scan(jnp.maximum, x, axis=axis)
    return vals


def cummin(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jax.lax.associative_scan(jnp.minimum, x, axis=axis)


def logcumsumexp(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    m = jnp.max(x, axis=axis, keepdims=True)
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    return jnp.log(jnp.cumsum(jnp.exp(x - m), axis=axis)) + m


def diff(x, n=1, axis=-1):
    return jnp.diff(x, n=n, axis=axis)


def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def kron(x, y):
    return jnp.kron(x, y)


def outer(x, y):
    return jnp.outer(x, y)


def inner(x, y):
    return jnp.inner(x, y)


def dot(x, y):
    return jnp.sum(x * y, axis=-1) if x.ndim <= 2 else jnp.dot(x, y)


def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    return jnp.matmul(x, y)


def bmm(x, y):
    return jnp.matmul(x, y)


def mm(x, y):
    return jnp.matmul(x, y)


def mv(x, vec):
    return jnp.matmul(x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * jnp.matmul(x, y)


def histogram(x, bins=100, min=0, max=0):
    rng = None if (min == 0 and max == 0) else (min, max)
    h, _ = jnp.histogram(x, bins=bins, range=rng)
    return h


def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=minlength)


def broadcast_shape(x_shape, y_shape):
    import numpy as np

    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def increment(x, value=1.0):
    return x + value


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale
