"""Tensor op namespace (ref: python/paddle/tensor/__init__.py)."""
from .creation import *  # noqa: F401,F403
from .creation import Tensor  # noqa: F401
from .logic import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from . import random  # noqa: F401

import jax.numpy as _jnp

einsum = _jnp.einsum
