"""Tensor op namespace (ref: python/paddle/tensor/__init__.py)."""
from .creation import *  # noqa: F401,F403
from .creation import Tensor  # noqa: F401
from .logic import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from . import random  # noqa: F401
from .random import (  # noqa: F401
    bernoulli,
    bernoulli_,
    exponential_,
    multinomial,
    normal,
    normal_,
    poisson,
    rand,
    randint,
    randint_like,
    randn,
    randperm,
    standard_gamma,
    standard_normal,
    uniform,
    uniform_,
)

import jax.numpy as _jnp

einsum = _jnp.einsum

# the op modules import jax/jnp/np at module scope; without __all__ the
# star imports above would re-export them as public tensor API — drop them
for _leak in ('jax', 'jnp', 'np', 'lax'):
    globals().pop(_leak, None)
del _leak
