"""Long-tail tensor ops (ref: python/paddle/tensor/{math,manipulation,
creation}.py — the remainder of paddle's top-level __all__).

Thin, composable jnp/lax wrappers: on TPU each of these is one or two
XLA HLOs; there is nothing kernel-shaped to hand-write. Semantics follow
the reference docstrings (paddle largely mirrors the numpy/torch
namesakes, which keeps the goldens honest).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [  # keeps `import *` from leaking jax/jnp/lax as paddle_tpu API
    'block_diag', 'hstack', 'vstack', 'dstack', 'column_stack', 'row_stack',
    'tensor_split', 'hsplit', 'vsplit', 'dsplit', 'unstack', 'atleast_1d',
    'atleast_2d', 'atleast_3d', 'diag_embed', 'diagonal', 'diagonal_scatter',
    'select_scatter', 'slice_scatter', 'index_fill', 'take', 'unflatten',
    'view_as', 'unfold', 'reverse', 'as_complex', 'as_real',
    'cartesian_prod', 'combinations', 'logaddexp', 'floor_mod', 'isneginf',
    'isposinf', 'isreal', 'isin', 'signbit', 'sgn', 'sinc', 'add_n',
    'nanmedian', 'nanquantile', 'histogram_bin_edges', 'histogramdd',
    'renorm', 'reduce_as', 'pdist', 'frexp', 'ldexp', 'trapezoid',
    'cumulative_trapezoid', 'vander', 'bitwise_left_shift',
    'bitwise_right_shift', 'gammaln', 'gammainc', 'gammaincc',
    'multigammaln', 'polygamma', 'i0e', 'i1', 'i1e', 'rank', 'shape',
    'tolist',
]

# ---- stacking / splitting ---------------------------------------------------


def block_diag(inputs):
    """ref: tensor/manipulation.py::block_diag."""
    mats = [jnp.atleast_2d(jnp.asarray(m)) for m in inputs]
    rows = sum(m.shape[0] for m in mats)
    cols = sum(m.shape[1] for m in mats)
    out = jnp.zeros((rows, cols), jnp.result_type(*mats))
    r = c = 0
    for m in mats:
        out = out.at[r:r + m.shape[0], c:c + m.shape[1]].set(m)
        r += m.shape[0]
        c += m.shape[1]
    return out


def hstack(x):
    return jnp.hstack([jnp.asarray(v) for v in x])


def vstack(x):
    return jnp.vstack([jnp.asarray(v) for v in x])


def dstack(x):
    return jnp.dstack([jnp.asarray(v) for v in x])


def column_stack(x):
    return jnp.column_stack([jnp.asarray(v) for v in x])


def row_stack(x):
    return jnp.vstack([jnp.asarray(v) for v in x])


def tensor_split(x, num_or_indices, axis=0):
    """ref: manipulation.py::tensor_split (uneven split allowed)."""
    x = jnp.asarray(x)
    if isinstance(num_or_indices, int):
        n = num_or_indices
        size = x.shape[axis]
        base, extra = divmod(size, n)
        sizes = [base + (1 if i < extra else 0) for i in range(n)]
        idx = jnp.cumsum(jnp.asarray(sizes))[:-1]
        return jnp.split(x, [int(i) for i in idx], axis=axis)
    return jnp.split(x, list(num_or_indices), axis=axis)


def hsplit(x, num_or_indices):
    if jnp.asarray(x).ndim < 1:
        raise ValueError('hsplit expects at least 1-D input')
    axis = 0 if jnp.asarray(x).ndim == 1 else 1
    return tensor_split(x, num_or_indices, axis=axis)


def vsplit(x, num_or_indices):
    if jnp.asarray(x).ndim < 2:
        raise ValueError('vsplit expects at least 2-D input')
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices):
    if jnp.asarray(x).ndim < 3:
        raise ValueError('dsplit expects at least 3-D input')
    return tensor_split(x, num_or_indices, axis=2)


def unstack(x, axis=0, num=None):
    """ref: manipulation.py::unstack — split and squeeze the axis."""
    x = jnp.asarray(x)
    n = x.shape[axis] if num is None else num
    return [jnp.squeeze(p, axis=axis) for p in jnp.split(x, n, axis=axis)]


def atleast_1d(*inputs):
    out = [jnp.atleast_1d(jnp.asarray(v)) for v in inputs]
    return out[0] if len(out) == 1 else out


def atleast_2d(*inputs):
    out = [jnp.atleast_2d(jnp.asarray(v)) for v in inputs]
    return out[0] if len(out) == 1 else out


def atleast_3d(*inputs):
    out = [jnp.atleast_3d(jnp.asarray(v)) for v in inputs]
    return out[0] if len(out) == 1 else out


# ---- rearrangement / scatter views ------------------------------------------


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    """Batched diagonal construction (ref: manipulation.py::diag_embed)."""
    x = jnp.asarray(x)
    n = x.shape[-1] + abs(offset)
    out_ndim = x.ndim + 1
    d1, d2 = dim1 % out_ndim, dim2 % out_ndim
    base = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    out = base.at[..., r, c].set(x)
    return jnp.moveaxis(out, (-2, -1), (d1, d2))


def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(jnp.asarray(x), offset=offset, axis1=axis1,
                        axis2=axis2)


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1):
    """Write `y` onto the (offset, axis1, axis2) diagonal of `x`
    (ref: manipulation.py::diagonal_scatter)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    a1, a2 = axis1 % x.ndim, axis2 % x.ndim
    moved = jnp.moveaxis(x, (a1, a2), (-2, -1))
    k = y.shape[-1]
    idx = jnp.arange(k)
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    moved = moved.at[..., r, c].set(y)
    return jnp.moveaxis(moved, (-2, -1), (a1, a2))


def select_scatter(x, values, axis, index):
    """ref: manipulation.py::select_scatter."""
    x = jnp.asarray(x)
    sl = [slice(None)] * x.ndim
    sl[axis] = index
    return x.at[tuple(sl)].set(values)


def slice_scatter(x, value, axes, starts, ends, strides):
    """ref: manipulation.py::slice_scatter."""
    x = jnp.asarray(x)
    sl = [slice(None)] * x.ndim
    for ax, st, en, sr in zip(axes, starts, ends, strides):
        sl[ax] = slice(st, en, sr)
    return x.at[tuple(sl)].set(value)


def index_fill(x, index, axis, value):
    """ref: manipulation.py::index_fill."""
    x = jnp.asarray(x)
    sl = [slice(None)] * x.ndim
    sl[axis] = jnp.asarray(index)
    return x.at[tuple(sl)].set(value)


def take(x, index, mode='raise'):
    """Flattened gather (ref: manipulation.py::take). mode: 'raise'
    (clip — no host roundtrip under jit), 'wrap', 'clip'."""
    x = jnp.asarray(x).reshape(-1)
    idx = jnp.asarray(index)
    n = x.shape[0]
    if mode == 'wrap':
        idx = ((idx % n) + n) % n
    else:
        idx = jnp.where(idx < 0, idx + n, idx)
        idx = jnp.clip(idx, 0, n - 1)
    return jnp.take(x, idx)


def unflatten(x, axis, shape):
    x = jnp.asarray(x)
    ax = axis % x.ndim
    shape = tuple(int(s) for s in shape)
    return x.reshape(x.shape[:ax] + shape + x.shape[ax + 1:])


def view_as(x, other):
    return jnp.asarray(x).reshape(jnp.asarray(other).shape)


def unfold(x, axis, size, step):
    """Sliding windows over one axis (ref: manipulation.py::unfold;
    torch.Tensor.unfold semantics — window dim appended last)."""
    x = jnp.asarray(x)
    ax = axis % x.ndim
    n = (x.shape[ax] - size) // step + 1
    starts = jnp.arange(n) * step
    idx = starts[:, None] + jnp.arange(size)[None]     # (n, size)
    out = jnp.take(x, idx.reshape(-1), axis=ax)
    out = out.reshape(x.shape[:ax] + (n, size) + x.shape[ax + 1:])
    return jnp.moveaxis(out, ax + 1, -1)


def reverse(x, axis):
    return jnp.flip(jnp.asarray(x), axis=axis)


def as_complex(x):
    x = jnp.asarray(x)
    return lax.complex(x[..., 0], x[..., 1])


def as_real(x):
    x = jnp.asarray(x)
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def cartesian_prod(x):
    """ref: manipulation.py::cartesian_prod."""
    arrs = [jnp.asarray(v).reshape(-1) for v in x]
    grids = jnp.meshgrid(*arrs, indexing='ij')
    out = jnp.stack([g.reshape(-1) for g in grids], axis=-1)
    return out[:, 0] if len(arrs) == 1 else out


def combinations(x, r=2, with_replacement=False):
    """ref: manipulation.py::combinations — index pattern is static."""
    import itertools

    x = jnp.asarray(x).reshape(-1)
    n = x.shape[0]
    gen = (itertools.combinations_with_replacement if with_replacement
           else itertools.combinations)
    idx = list(gen(range(n), r))
    if not idx:
        return jnp.zeros((0, r), x.dtype)
    return x[jnp.asarray(idx)]


# ---- math long tail ---------------------------------------------------------


def logaddexp(x, y):
    return jnp.logaddexp(x, y)


def floor_mod(x, y):
    return jnp.mod(x, y)


def isneginf(x):
    return jnp.isneginf(x)


def isposinf(x):
    return jnp.isposinf(x)


def isreal(x):
    return jnp.isreal(x)


def isin(x, test_x, assume_unique=False, invert=False):
    return jnp.isin(x, test_x, assume_unique=assume_unique, invert=invert)


def signbit(x):
    return jnp.signbit(x)


def sgn(x):
    """Sign for real, unit phase for complex (ref: math.py::sgn)."""
    x = jnp.asarray(x)
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        mag = jnp.abs(x)
        return jnp.where(mag == 0, 0, x / jnp.where(mag == 0, 1, mag))
    return jnp.sign(x)


def sinc(x):
    return jnp.sinc(x)


def add_n(inputs):
    out = jnp.asarray(inputs[0])
    for v in inputs[1:]:
        out = out + jnp.asarray(v)
    return out


def nanmedian(x, axis=None, keepdim=False):
    return jnp.nanmedian(jnp.asarray(x), axis=axis, keepdims=keepdim)


def nanquantile(x, q, axis=None, keepdim=False):
    return jnp.nanquantile(jnp.asarray(x), q, axis=axis, keepdims=keepdim)


def histogram_bin_edges(x, bins=100, min=0, max=0):
    x = jnp.asarray(x).reshape(-1).astype(jnp.float32)
    lo, hi = (jnp.min(x), jnp.max(x)) if min == 0 and max == 0 else (min, max)
    lo, hi = jnp.where(lo == hi, lo - 0.5, lo), jnp.where(lo == hi, hi + 0.5, hi)
    return jnp.linspace(lo, hi, bins + 1)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None):
    x = jnp.asarray(x)
    return jnp.histogramdd(x, bins=bins, range=ranges, density=density,
                           weights=weights)


def renorm(x, p, axis, max_norm):
    """Clip per-slice p-norms to max_norm (ref: math.py::renorm)."""
    x = jnp.asarray(x)
    ax = axis % x.ndim
    other = tuple(i for i in range(x.ndim) if i != ax)
    norms = jnp.sum(jnp.abs(x) ** p, axis=other, keepdims=True) ** (1.0 / p)
    scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * scale


def reduce_as(x, target):
    """Sum-reduce x to target's (broadcastable) shape
    (ref: math.py::reduce_as)."""
    x = jnp.asarray(x)
    tshape = jnp.asarray(target).shape
    lead = x.ndim - len(tshape)
    axes = tuple(range(lead)) + tuple(
        lead + i for i, s in enumerate(tshape) if s == 1 and x.shape[lead + i] != 1)
    out = jnp.sum(x, axis=axes, keepdims=False)
    return out.reshape(tshape)


def pdist(x, p=2.0):
    """Condensed pairwise distances (ref: math.py::pdist)."""
    x = jnp.asarray(x)
    n = x.shape[0]
    diff = x[:, None] - x[None]
    if p == 2.0:
        d = jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 0.0)
    elif p == 0:
        d = jnp.sum(diff != 0, axis=-1).astype(x.dtype)
    elif p == float('inf'):
        d = jnp.max(jnp.abs(diff), axis=-1)
    else:
        d = jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)
    iu = jnp.triu_indices(n, k=1)
    return d[iu]


def frexp(x):
    return jnp.frexp(x)


def ldexp(x, y):
    return jnp.ldexp(x, y)


def trapezoid(y, x=None, dx=None, axis=-1):
    return jnp.trapezoid(jnp.asarray(y), x=x, dx=1.0 if dx is None else dx,
                         axis=axis)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1):
    """ref: math.py::cumulative_trapezoid."""
    y = jnp.asarray(y)
    d = (jnp.diff(jnp.asarray(x), axis=axis) if x is not None
         else (1.0 if dx is None else dx))
    ax = axis % y.ndim
    sl1 = [slice(None)] * y.ndim
    sl2 = [slice(None)] * y.ndim
    sl1[ax] = slice(1, None)
    sl2[ax] = slice(None, -1)
    avg = (y[tuple(sl1)] + y[tuple(sl2)]) / 2.0
    return jnp.cumsum(avg * d, axis=ax)


def vander(x, n=None, increasing=False):
    return jnp.vander(jnp.asarray(x), N=n, increasing=increasing)


def bitwise_left_shift(x, y, is_arithmetic=True):
    return jnp.left_shift(x, y)


def bitwise_right_shift(x, y, is_arithmetic=True):
    x = jnp.asarray(x)
    if is_arithmetic:
        return jnp.right_shift(x, y)
    # logical shift: operate on the unsigned view
    info = jnp.iinfo(x.dtype)
    ux = x.astype(jnp.dtype(f'uint{info.bits}'))
    return jnp.right_shift(ux, jnp.asarray(y).astype(ux.dtype)).astype(x.dtype)


# ---- special functions ------------------------------------------------------


def gammaln(x):
    return jax.scipy.special.gammaln(x)


def gammainc(x, y):
    """Regularized lower incomplete gamma P(x, y) (ref: math.py::gammainc)."""
    return jax.scipy.special.gammainc(x, y)


def gammaincc(x, y):
    return jax.scipy.special.gammaincc(x, y)


def multigammaln(x, p):
    return jax.scipy.special.multigammaln(x, p)


def polygamma(x, n):
    if n == 0:
        return jax.scipy.special.digamma(x)
    return jax.scipy.special.polygamma(n, x)


def i0e(x):
    return jax.scipy.special.i0e(x)


def i1(x):
    return jax.scipy.special.i1(x)


def i1e(x):
    return jax.scipy.special.i1e(x)


# ---- attribute-style helpers (ref: tensor/attribute.py) ---------------------


def rank(x):
    return jnp.asarray(jnp.asarray(x).ndim)


def shape(x):
    """Shape as a tensor (ref: paddle.shape)."""
    return jnp.asarray(jnp.asarray(x).shape, jnp.int32)


def tolist(x):
    import numpy as _np

    return _np.asarray(x).tolist()
