"""Linear algebra (ref: python/paddle/tensor/linalg.py, python/paddle/linalg.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def norm(x, p=None, axis=None, keepdim=False):
    if p is None:
        p = 'fro' if axis is None or isinstance(axis, (list, tuple)) else 2
    if p == 'fro' and axis is None:
        return jnp.sqrt(jnp.sum(jnp.square(x)))
    axis_t = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.linalg.norm(x, ord=p, axis=axis_t, keepdims=keepdim)


def vector_norm(x, p=2, axis=None, keepdim=False):
    return jnp.linalg.vector_norm(x, ord=p, axis=axis, keepdims=keepdim)


def matrix_norm(x, p='fro', axis=(-2, -1), keepdim=False):
    return jnp.linalg.matrix_norm(x, ord=p, keepdims=keepdim)


def cond(x, p=None):
    return jnp.linalg.cond(x, p=p)


def det(x):
    return jnp.linalg.det(x)


def slogdet(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


def inv(x):
    return jnp.linalg.inv(x)


def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


def solve(x, y):
    return jnp.linalg.solve(x, y)


def lstsq(x, y, rcond=None):
    return jnp.linalg.lstsq(x, y, rcond=rcond)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
    )


def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


def cholesky_solve(x, y, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


def lu(x, pivot=True, get_infos=False):
    """ref: paddle.linalg.lu — pivots are 1-based sequential row swaps
    (LAPACK ipiv), not 0-based like jax's lu_factor."""
    lu_, piv = jax.scipy.linalg.lu_factor(x)
    piv = (piv + 1).astype(jnp.int32)
    if get_infos:
        return lu_, piv, jnp.zeros((), jnp.int32)
    return lu_, piv


def qr(x, mode='reduced'):
    return jnp.linalg.qr(x, mode=mode)


def svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


def svdvals(x):
    return jnp.linalg.svd(x, compute_uv=False)


def eig(x):
    return jnp.linalg.eig(x)


def eigh(x, UPLO='L'):
    return jnp.linalg.eigh(x, UPLO=UPLO)


def eigvals(x):
    return jnp.linalg.eigvals(x)


def eigvalsh(x, UPLO='L'):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol)


def multi_dot(xs):
    return jnp.linalg.multi_dot(xs)


def cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=axis)


def householder_product(x, tau):
    m, n = x.shape[-2], x.shape[-1]
    eye = jnp.eye(m, dtype=x.dtype)

    def body(i, q):
        v = jnp.where(jnp.arange(m) < i, 0.0, x[..., :, i])
        v = v.at[i].set(1.0)
        h = eye - tau[..., i] * jnp.outer(v, v)
        return q @ h

    return jax.lax.fori_loop(0, n, body, eye)[..., :, :n]


def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0, fweights=fweights, aweights=aweights)


def pca_lowrank(x, q=None, center=True, niter=2):
    if center:
        x = x - jnp.mean(x, axis=-2, keepdims=True)
    u, s, vt = jnp.linalg.svd(x, full_matrices=False)
    q = q or min(6, *x.shape[-2:])
    return u[..., :q], s[..., :q], jnp.swapaxes(vt, -1, -2)[..., :q]


def cholesky_inverse(x, upper=False):
    """ref: paddle.linalg.cholesky_inverse — inverse of A from its
    Cholesky factor via two triangular solves (no explicit inverse)."""
    x = jnp.asarray(x)
    eye = jnp.eye(x.shape[-1], dtype=x.dtype)
    l = x.T if upper else x
    y = jax.scipy.linalg.solve_triangular(l, eye, lower=True)
    return y.T @ y


def matrix_exp(x):
    """ref: paddle.linalg.matrix_exp."""
    return jax.scipy.linalg.expm(jnp.asarray(x))


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True):
    """ref: paddle.linalg.lu_unpack — split packed LU into (P, L, U)."""
    lu_data = jnp.asarray(lu_data)
    m, n = lu_data.shape[-2:]
    k = min(m, n)
    l = jnp.tril(lu_data[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_data.dtype)
    u = jnp.triu(lu_data[..., :k, :])
    if not unpack_pivots:
        return None, l, u
    # pivots (1-based sequential row swaps) -> permutation matrix,
    # vmapped over any leading batch dims
    piv = jnp.asarray(lu_pivots).astype(jnp.int32) - 1
    npiv = piv.shape[-1]

    def one_perm(p1):
        perm = jnp.arange(m)
        for i in range(npiv):
            j = p1[i]
            pi, pj = perm[i], perm[j]
            perm = perm.at[i].set(pj).at[j].set(pi)
        return jnp.eye(m, dtype=lu_data.dtype)[perm].T

    if piv.ndim == 1:
        p = one_perm(piv)
    else:
        batch = piv.shape[:-1]
        p = jax.vmap(one_perm)(piv.reshape(-1, npiv))
        p = p.reshape(batch + (m, m))
    out = (p, l, u) if unpack_ludata else (p, None, None)
    return out


def svd_lowrank(x, q=6, niter=2, M=None):
    """Randomized low-rank SVD (ref: paddle.linalg.svd_lowrank; Halko
    et al. randomized range finder + small exact SVD)."""
    from ..framework import random as random_mod

    x = jnp.asarray(x).astype(jnp.float32)
    if M is not None:
        x = x - jnp.asarray(M)
    m, n = x.shape[-2:]
    q = min(q, m, n)
    key = random_mod.split_key()
    omega = jax.random.normal(key, (n, q), x.dtype)
    xt = jnp.swapaxes(x, -1, -2)          # batch-safe transpose
    # randomized range finder with per-step QR re-orthonormalization —
    # bare power iteration in fp32 collapses the small singular directions
    qmat, _ = jnp.linalg.qr(x @ omega)
    for _ in range(niter):
        z, _ = jnp.linalg.qr(xt @ qmat)
        qmat, _ = jnp.linalg.qr(x @ z)
    b = jnp.swapaxes(qmat, -1, -2) @ x
    u_b, s, vt = jnp.linalg.svd(b, full_matrices=False)
    return qmat @ u_b, s, jnp.swapaxes(vt, -1, -2)


def ormqr(x, tau, y, left=True, transpose=False):
    """Multiply y by Q = H_0 H_1 ... H_{k-1} from LAPACK-layout
    Householder data (ref: paddle.linalg.ormqr). x: (m, k) reflectors
    below the diagonal, tau: (k,).

    Reflectors are applied to y directly — O(m n k), no m*m Q is ever
    materialized (the tall-skinny case LAPACK's ormqr exists for)."""
    x = jnp.asarray(x)
    tau = jnp.asarray(tau)
    m, k = x.shape[-2], tau.shape[-1]

    def apply_q(z, reverse):
        # z: (m, n). Q @ z applies H_i for i = k-1..0; Q^T @ z ascending.
        order = range(k - 1, -1, -1) if reverse else range(k)
        for i in order:
            v = jnp.zeros((m,), x.dtype).at[i].set(1.0)
            v = v.at[i + 1:].set(x[i + 1:, i])
            z = z - tau[i] * jnp.outer(v, v @ z)
        return z

    y = jnp.asarray(y)
    if left:
        # Q @ y (reverse order) or Q^T @ y (ascending)
        return apply_q(y, reverse=not transpose)
    # y @ Q = (Q^T y^T)^T;  y @ Q^T = (Q y^T)^T
    zt = apply_q(jnp.swapaxes(y, -1, -2), reverse=transpose)
    return jnp.swapaxes(zt, -1, -2)


def fp8_fp8_half_gemm_fused(x, y, bias=None, transpose_x=False,
                            transpose_y=False, scale=1.0,
                            output_dtype='bfloat16', activation=None):
    """ref: paddle.linalg.fp8_fp8_half_gemm_fused (cuBLASLt fp8 GEMM).
    TPU path: the pallas fp8 weight-only kernel when y is pre-quantized
    fp8, else an XLA dot with fp8 inputs upcast in the MXU."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x.astype(jnp.float32), y.astype(jnp.float32)) * scale
    if bias is not None:
        out = out + jnp.asarray(bias, jnp.float32)
    if activation in ('gelu',):
        out = jax.nn.gelu(out)
    elif activation in ('relu',):
        out = jnp.maximum(out, 0)
    return out.astype(output_dtype)


def inverse(x, name=None):
    """ref: tensor/math.py::inverse — alias of linalg.inv."""
    return inv(x)
