"""Linear algebra (ref: python/paddle/tensor/linalg.py, python/paddle/linalg.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def norm(x, p=None, axis=None, keepdim=False):
    if p is None:
        p = 'fro' if axis is None or isinstance(axis, (list, tuple)) else 2
    if p == 'fro' and axis is None:
        return jnp.sqrt(jnp.sum(jnp.square(x)))
    axis_t = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.linalg.norm(x, ord=p, axis=axis_t, keepdims=keepdim)


def vector_norm(x, p=2, axis=None, keepdim=False):
    return jnp.linalg.vector_norm(x, ord=p, axis=axis, keepdims=keepdim)


def matrix_norm(x, p='fro', axis=(-2, -1), keepdim=False):
    return jnp.linalg.matrix_norm(x, ord=p, keepdims=keepdim)


def cond(x, p=None):
    return jnp.linalg.cond(x, p=p)


def det(x):
    return jnp.linalg.det(x)


def slogdet(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


def inv(x):
    return jnp.linalg.inv(x)


def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


def solve(x, y):
    return jnp.linalg.solve(x, y)


def lstsq(x, y, rcond=None):
    return jnp.linalg.lstsq(x, y, rcond=rcond)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
    )


def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


def cholesky_solve(x, y, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


def lu(x, pivot=True):
    lu_, piv = jax.scipy.linalg.lu_factor(x)
    return lu_, piv


def qr(x, mode='reduced'):
    return jnp.linalg.qr(x, mode=mode)


def svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


def svdvals(x):
    return jnp.linalg.svd(x, compute_uv=False)


def eig(x):
    return jnp.linalg.eig(x)


def eigh(x, UPLO='L'):
    return jnp.linalg.eigh(x, UPLO=UPLO)


def eigvals(x):
    return jnp.linalg.eigvals(x)


def eigvalsh(x, UPLO='L'):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol)


def multi_dot(xs):
    return jnp.linalg.multi_dot(xs)


def cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=axis)


def householder_product(x, tau):
    m, n = x.shape[-2], x.shape[-1]
    eye = jnp.eye(m, dtype=x.dtype)

    def body(i, q):
        v = jnp.where(jnp.arange(m) < i, 0.0, x[..., :, i])
        v = v.at[i].set(1.0)
        h = eye - tau[..., i] * jnp.outer(v, v)
        return q @ h

    return jax.lax.fori_loop(0, n, body, eye)[..., :, :n]


def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0, fweights=fweights, aweights=aweights)


def pca_lowrank(x, q=None, center=True, niter=2):
    if center:
        x = x - jnp.mean(x, axis=-2, keepdims=True)
    u, s, vt = jnp.linalg.svd(x, full_matrices=False)
    q = q or min(6, *x.shape[-2:])
    return u[..., :q], s[..., :q], jnp.swapaxes(vt, -1, -2)[..., :q]
