"""Tensor creation ops (ref: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod


def _dt(dtype, default=None):
    d = dtype_mod.convert_dtype(dtype)
    if d is None:
        d = default
    return d


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """ref: paddle.to_tensor."""
    if isinstance(data, jax.Array) and dtype is None:
        return data
    arr = jnp.asarray(data)
    d = _dt(dtype)
    if d is None and arr.dtype == jnp.float64:
        d = dtype_mod.get_default_dtype()
    return arr.astype(d) if d is not None else arr


Tensor = jax.Array


def zeros(shape, dtype=None):
    return jnp.zeros(shape, _dt(dtype, dtype_mod.get_default_dtype()))


def ones(shape, dtype=None):
    return jnp.ones(shape, _dt(dtype, dtype_mod.get_default_dtype()))


def full(shape, fill_value, dtype=None):
    return jnp.full(shape, fill_value, _dt(dtype))


def zeros_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=_dt(dtype))


def ones_like(x, dtype=None):
    return jnp.ones_like(x, dtype=_dt(dtype))


def full_like(x, fill_value, dtype=None):
    return jnp.full_like(x, fill_value, dtype=_dt(dtype))


def empty(shape, dtype=None):
    return jnp.zeros(shape, _dt(dtype, dtype_mod.get_default_dtype()))


def empty_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=_dt(dtype))


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    return jnp.arange(start, end, step, dtype=_dt(dtype))


def linspace(start, stop, num, dtype=None):
    return jnp.linspace(start, stop, int(num), dtype=_dt(dtype))


def logspace(start, stop, num, base=10.0, dtype=None):
    return jnp.logspace(start, stop, int(num), base=base, dtype=_dt(dtype))


def eye(num_rows, num_columns=None, dtype=None):
    return jnp.eye(num_rows, num_columns, dtype=_dt(dtype, dtype_mod.get_default_dtype()))


def diag(x, offset=0, padding_value=0):
    if jnp.ndim(x) == 1 and padding_value != 0:
        n = x.shape[0] + abs(offset)
        out = jnp.full((n, n), padding_value, dtype=x.dtype)
        idx = jnp.arange(x.shape[0])
        r = idx + max(0, -offset)
        c = idx + max(0, offset)
        return out.at[r, c].set(x)
    return jnp.diag(x, k=offset)


def diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


def meshgrid(*args):
    return jnp.meshgrid(*args, indexing='ij')


def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


def tril_indices(row, col, offset=0):
    return jnp.stack(jnp.tril_indices(row, k=offset, m=col))


def triu_indices(row, col, offset=0):
    return jnp.stack(jnp.triu_indices(row, k=offset, m=col))


def assign(x, output=None):
    return jnp.asarray(x)


def clone(x):
    return jnp.array(x, copy=True)


def complex(real, imag):
    return jax.lax.complex(real, imag)


def polar(abs, angle):
    return jax.lax.complex(abs * jnp.cos(angle), abs * jnp.sin(angle))


def numel(x):
    return int(np.prod(x.shape)) if not isinstance(x.shape[0] if x.shape else 0, jax.core.Tracer) else x.size


def one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes)


def create_tensor(dtype='float32', name=None, persistable=False):
    """ref: tensor/creation.py::create_tensor — an empty, typed tensor
    placeholder (the reference returns an uninitialised variable)."""
    from ..framework import dtype as dtype_mod
    return jnp.zeros((0,), dtype=dtype_mod.convert_dtype(dtype or 'float32'))
