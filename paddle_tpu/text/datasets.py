"""Text datasets (ref: python/paddle/text/datasets/*).

Download-free, like `vision.datasets`: each dataset reads the reference's
standard local archive when a path is supplied, otherwise serves
deterministic synthetic data with the right shapes/vocab for tests and
smoke training (no network egress in this environment).
"""
from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from ..io.dataset import Dataset


class UCIHousing(Dataset):
    """13-feature housing regression (ref: text/datasets/uci_housing.py).
    Reads the whitespace `housing.data` file when `data_file` is given."""

    FEATURES = 13

    def __init__(self, data_file=None, mode='train'):
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype(np.float32)
        else:
            rng = np.random.default_rng(0)
            x = rng.normal(size=(506, self.FEATURES)).astype(np.float32)
            w = rng.normal(size=(self.FEATURES,)).astype(np.float32)
            y = x @ w + rng.normal(scale=0.1, size=(506,)).astype(np.float32)
            raw = np.concatenate([x, y[:, None]], axis=1)
        # reference normalizes features to [0, 1] by min/max then splits 80/20
        feats, target = raw[:, :-1], raw[:, -1:]
        lo, hi = feats.min(0), feats.max(0)
        feats = (feats - lo) / np.maximum(hi - lo, 1e-8)
        split = int(len(feats) * 0.8)
        if mode == 'train':
            self.data, self.target = feats[:split], target[:split]
        else:
            self.data, self.target = feats[split:], target[split:]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i], self.target[i]


class Imdb(Dataset):
    """IMDB sentiment (ref: text/datasets/imdb.py): word-id sequences +
    0/1 labels. Reads the aclImdb tar when `data_file` is given."""

    def __init__(self, data_file=None, mode='train', cutoff=150,
                 vocab_size=2000, size=512, max_len=64):
        self.word_idx = {f'w{i}': i for i in range(vocab_size)}
        if data_file and os.path.exists(data_file):
            self.docs, self.labels = self._load_tar(data_file, mode, cutoff)
        else:
            rng = np.random.default_rng(1 if mode == 'train' else 2)
            lens = rng.integers(8, max_len, size)
            self.docs = [rng.integers(0, vocab_size, n).astype(np.int64)
                         for n in lens]
            self.labels = rng.integers(0, 2, size).astype(np.int64)

    def _load_tar(self, path, mode, cutoff):
        docs, labels = [], []
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                name = member.name
                if f'/{mode}/' not in name or not name.endswith('.txt'):
                    continue
                if '/pos/' in name:
                    lab = 1
                elif '/neg/' in name:
                    lab = 0
                else:
                    continue
                words = tf.extractfile(member).read().decode(
                    'utf-8', 'ignore').lower().split()
                ids = [self.word_idx.get(w, len(self.word_idx))
                       for w in words]
                docs.append(np.asarray(ids, np.int64))
                labels.append(lab)
        return docs, np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, i):
        return self.docs[i], self.labels[i]


class Imikolov(Dataset):
    """PTB-style n-gram LM tuples (ref: text/datasets/imikolov.py)."""

    def __init__(self, data_file=None, data_type='NGRAM', window_size=5,
                 mode='train', vocab_size=2000, size=2048):
        if data_type not in ('NGRAM', 'SEQ'):
            raise ValueError(f'bad data_type: {data_type}')
        rng = np.random.default_rng(3 if mode == 'train' else 4)
        if data_type == 'NGRAM':
            self.data = rng.integers(
                0, vocab_size, (size, window_size)).astype(np.int64)
        else:
            self.data = [
                (rng.integers(0, vocab_size, 10).astype(np.int64),
                 rng.integers(0, vocab_size, 10).astype(np.int64))
                for _ in range(size)]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i]
