"""paddle_tpu.text (ref: python/paddle/text/__init__.py): Viterbi
decoding + download-free text datasets."""
from .datasets import Imdb, Imikolov, UCIHousing  # noqa: F401
from .viterbi_decode import ViterbiDecoder, viterbi_decode  # noqa: F401

__all__ = ['viterbi_decode', 'ViterbiDecoder', 'UCIHousing', 'Imdb',
           'Imikolov']
