"""Viterbi decoding (ref: python/paddle/text/viterbi_decode.py).

The reference runs a fused C++ kernel; here the forward max-product pass
is a `lax.scan` over time (static shapes, batch-parallel on the VPU) and
the backtrace is a reverse `lax.scan` over the stored argmax history —
both jit-safe. Variable lengths are handled by masking: once t reaches a
sequence's length the alpha row freezes and the history records the
identity permutation, so a uniform backtrace from the last step recovers
the path ending at each sequence's own final step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True):
    """Highest-scoring tag sequence under unary `potentials`
    [batch, seq, num_tags] and pairwise `transition_params`
    [num_tags, num_tags]; `lengths` [batch].

    Returns (scores [batch], paths [batch, max(lengths)] int64 — padded
    with 0 past each sequence's length; under jit the path length is the
    static seq dim instead, since dynamic output shapes cannot trace).

    With `include_bos_eos_tag`, the last tag index is the implicit start
    tag and the second-to-last the stop tag, matching the reference.
    """
    potentials = jnp.asarray(potentials)
    trans = jnp.asarray(transition_params)
    lengths = jnp.asarray(lengths).astype(jnp.int32)
    batch, seq, num_tags = potentials.shape

    alpha = potentials[:, 0]
    if include_bos_eos_tag:
        alpha = alpha + trans[num_tags - 1][None]   # start -> first tag

    def step(alpha, inp):
        emit, t = inp                               # (B, N), scalar t
        scores = alpha[:, :, None] + trans[None]    # (B, prev, cur)
        best_prev = jnp.argmax(scores, axis=1)
        new_alpha = jnp.max(scores, axis=1) + emit
        valid = (t < lengths)[:, None]
        hist = jnp.where(valid, best_prev, jnp.arange(num_tags)[None])
        return jnp.where(valid, new_alpha, alpha), hist

    if seq > 1:
        alpha, hist = lax.scan(
            step, alpha,
            (potentials[:, 1:].transpose(1, 0, 2), jnp.arange(1, seq)))
    else:
        hist = jnp.zeros((0, batch, num_tags), jnp.int32)
    if include_bos_eos_tag:
        alpha = alpha + trans[:, num_tags - 2][None]  # last tag -> stop

    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1)

    def back(tag, h):
        return jnp.take_along_axis(h, tag[:, None], axis=1)[:, 0], tag

    first_tag, tags = lax.scan(back, last_tag, hist, reverse=True)
    paths = jnp.concatenate([first_tag[:, None], tags.transpose(1, 0)],
                            axis=1).astype(jnp.int64)
    paths = jnp.where(jnp.arange(seq)[None] < lengths[:, None], paths, 0)
    if not isinstance(lengths, jax.core.Tracer):
        paths = paths[:, :int(jnp.max(lengths))]    # eager: match reference
    return scores, paths


class ViterbiDecoder:
    """Callable wrapper holding `transition_params`
    (ref: python/paddle/text/viterbi_decode.py::ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
