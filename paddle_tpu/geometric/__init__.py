"""paddle_tpu.geometric (ref: python/paddle/geometric) — graph segment
math + message passing over XLA segment/scatter primitives.

The reference's CUDA graph_send_recv kernels become `jax.ops.segment_*`
reductions (sorted-scatter under the hood, TPU-friendly); `num_segments`
/ `out_size` must be static under jit, matching the reference's
requirement that out_size be known for the static graph. Sampling /
reindex (host-side graph preprocessing, ref geometric/sampling) stay on
numpy — they are data-pipeline utilities, not device code.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    'segment_sum', 'segment_mean', 'segment_min', 'segment_max',
    'send_u_recv', 'send_ue_recv', 'send_uv',
]


def _num_segments(segment_ids, n):
    if n is not None:
        return int(n)
    if isinstance(segment_ids, jax.core.Tracer):
        raise ValueError(
            'num_segments must be passed explicitly under jit '
            '(the output size must be static)')
    import numpy as np

    return int(np.asarray(jnp.max(segment_ids)) + 1)


def segment_sum(data, segment_ids, num_segments=None):
    """ref: paddle.geometric.segment_sum (geometric/math.py:29)."""
    return jax.ops.segment_sum(data, segment_ids,
                               _num_segments(segment_ids, num_segments))


def segment_mean(data, segment_ids, num_segments=None):
    """ref: geometric/math.py:88 — empty segments yield 0 like the ref."""
    n = _num_segments(segment_ids, num_segments)
    tot = jax.ops.segment_sum(data, segment_ids, n)
    cnt = jax.ops.segment_sum(jnp.ones_like(segment_ids, data.dtype),
                              segment_ids, n)
    shape = (n,) + (1,) * (data.ndim - 1)
    return tot / jnp.maximum(cnt.reshape(shape), 1)


def segment_min(data, segment_ids, num_segments=None):
    """ref: geometric/math.py:149 — empty segments yield 0 like the ref."""
    n = _num_segments(segment_ids, num_segments)
    out = jax.ops.segment_min(data, segment_ids, n)
    return _zero_empty(out, segment_ids, n, data)


def segment_max(data, segment_ids, num_segments=None):
    """ref: geometric/math.py:209 — empty segments yield 0 like the ref."""
    n = _num_segments(segment_ids, num_segments)
    out = jax.ops.segment_max(data, segment_ids, n)
    return _zero_empty(out, segment_ids, n, data)


def _zero_empty(out, segment_ids, n, data):
    cnt = jax.ops.segment_sum(jnp.ones_like(segment_ids), segment_ids, n)
    shape = (n,) + (1,) * (data.ndim - 1)
    return jnp.where(cnt.reshape(shape) > 0, out, 0)


_REDUCERS = {
    'sum': jax.ops.segment_sum,
    'add': jax.ops.segment_sum,
    'mean': None,                      # handled via sum/count
    'min': jax.ops.segment_min,
    'max': jax.ops.segment_max,
}


def send_u_recv(x, src_index, dst_index, reduce_op='sum', out_size=None):
    """ref: geometric/message_passing/send_recv.py:55 — gather src-node
    features along edges, reduce at dst nodes."""
    return send_ue_recv(x, None, src_index, dst_index, 'add', reduce_op,
                        out_size)


def send_ue_recv(x, y, src_index, dst_index, message_op='add',
                 reduce_op='sum', out_size=None):
    """ref: send_recv.py:210 — combine src features with edge features
    (add/sub/mul/div), reduce at dst."""
    if reduce_op not in _REDUCERS:
        raise ValueError(f"reduce_op must be one of {list(_REDUCERS)}")
    msg = x[src_index]                                  # (E, ...)
    if y is not None:
        y = jnp.asarray(y)
        if y.ndim < msg.ndim:                           # per-edge scalar
            y = y.reshape(y.shape + (1,) * (msg.ndim - y.ndim))
        msg = {'add': msg + y, 'sub': msg - y, 'mul': msg * y,
               'div': msg / y}[message_op]
    n = out_size if out_size is not None else x.shape[0]
    if reduce_op == 'mean':
        return segment_mean(msg, dst_index, n)
    out = _REDUCERS[reduce_op](msg, dst_index, n)
    if reduce_op in ('min', 'max'):
        out = _zero_empty(out, dst_index, n, msg)
    return out


def send_uv(x, y, src_index, dst_index, message_op='add'):
    """ref: send_recv.py:413 — per-edge message from src (x) and dst (y)
    node features, no reduction."""
    xs = x[src_index]
    yd = y[dst_index]
    return {'add': xs + yd, 'sub': xs - yd, 'mul': xs * yd,
            'div': xs / yd}[message_op]
