"""paddle_tpu.geometric (ref: python/paddle/geometric) — graph segment
math + message passing over XLA segment/scatter primitives.

The reference's CUDA graph_send_recv kernels become `jax.ops.segment_*`
reductions (sorted-scatter under the hood, TPU-friendly); `num_segments`
/ `out_size` must be static under jit, matching the reference's
requirement that out_size be known for the static graph. Sampling /
reindex (host-side graph preprocessing, ref geometric/sampling) stay on
numpy — they are data-pipeline utilities, not device code.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    'segment_sum', 'segment_mean', 'segment_min', 'segment_max',
    'send_u_recv', 'send_ue_recv', 'send_uv',
]


def _num_segments(segment_ids, n):
    if n is not None:
        return int(n)
    if isinstance(segment_ids, jax.core.Tracer):
        raise ValueError(
            'num_segments must be passed explicitly under jit '
            '(the output size must be static)')
    import numpy as np

    return int(np.asarray(jnp.max(segment_ids)) + 1)


def segment_sum(data, segment_ids, num_segments=None):
    """ref: paddle.geometric.segment_sum (geometric/math.py:29)."""
    return jax.ops.segment_sum(data, segment_ids,
                               _num_segments(segment_ids, num_segments))


def segment_mean(data, segment_ids, num_segments=None):
    """ref: geometric/math.py:88 — empty segments yield 0 like the ref."""
    n = _num_segments(segment_ids, num_segments)
    tot = jax.ops.segment_sum(data, segment_ids, n)
    cnt = jax.ops.segment_sum(jnp.ones_like(segment_ids, data.dtype),
                              segment_ids, n)
    shape = (n,) + (1,) * (data.ndim - 1)
    return tot / jnp.maximum(cnt.reshape(shape), 1)


def segment_min(data, segment_ids, num_segments=None):
    """ref: geometric/math.py:149 — empty segments yield 0 like the ref."""
    n = _num_segments(segment_ids, num_segments)
    out = jax.ops.segment_min(data, segment_ids, n)
    return _zero_empty(out, segment_ids, n, data)


def segment_max(data, segment_ids, num_segments=None):
    """ref: geometric/math.py:209 — empty segments yield 0 like the ref."""
    n = _num_segments(segment_ids, num_segments)
    out = jax.ops.segment_max(data, segment_ids, n)
    return _zero_empty(out, segment_ids, n, data)


def _zero_empty(out, segment_ids, n, data):
    cnt = jax.ops.segment_sum(jnp.ones_like(segment_ids), segment_ids, n)
    shape = (n,) + (1,) * (data.ndim - 1)
    return jnp.where(cnt.reshape(shape) > 0, out, 0)


_REDUCERS = {
    'sum': jax.ops.segment_sum,
    'add': jax.ops.segment_sum,
    'mean': None,                      # handled via sum/count
    'min': jax.ops.segment_min,
    'max': jax.ops.segment_max,
}


def send_u_recv(x, src_index, dst_index, reduce_op='sum', out_size=None):
    """ref: geometric/message_passing/send_recv.py:55 — gather src-node
    features along edges, reduce at dst nodes."""
    return send_ue_recv(x, None, src_index, dst_index, 'add', reduce_op,
                        out_size)


def send_ue_recv(x, y, src_index, dst_index, message_op='add',
                 reduce_op='sum', out_size=None):
    """ref: send_recv.py:210 — combine src features with edge features
    (add/sub/mul/div), reduce at dst."""
    if reduce_op not in _REDUCERS:
        raise ValueError(f"reduce_op must be one of {list(_REDUCERS)}")
    msg = x[src_index]                                  # (E, ...)
    if y is not None:
        y = jnp.asarray(y)
        if y.ndim < msg.ndim:                           # per-edge scalar
            y = y.reshape(y.shape + (1,) * (msg.ndim - y.ndim))
        msg = {'add': msg + y, 'sub': msg - y, 'mul': msg * y,
               'div': msg / y}[message_op]
    n = out_size if out_size is not None else x.shape[0]
    if reduce_op == 'mean':
        return segment_mean(msg, dst_index, n)
    out = _REDUCERS[reduce_op](msg, dst_index, n)
    if reduce_op in ('min', 'max'):
        out = _zero_empty(out, dst_index, n, msg)
    return out


def send_uv(x, y, src_index, dst_index, message_op='add'):
    """ref: send_recv.py:413 — per-edge message from src (x) and dst (y)
    node features, no reduction."""
    xs = x[src_index]
    yd = y[dst_index]
    return {'add': xs + yd, 'sub': xs - yd, 'mul': xs * yd,
            'div': xs / yd}[message_op]


# ---- graph sampling / reindex (ref: python/paddle/geometric/sampling,
# reindex). Host-side: neighbour sampling is data-dependent control flow
# the reference also runs as a host-orchestrated kernel.

def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None):
    """ref: paddle.geometric.reindex_graph — relabel nodes+neighbors to
    contiguous local ids. Returns (reindex_src, reindex_dst, out_nodes)."""
    from ..incubate import graph_reindex

    return graph_reindex(x, neighbors, count, value_buffer, index_buffer)


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None):
    """ref: paddle.geometric.reindex_heter_graph — like reindex_graph
    with per-edge-type neighbor/count lists sharing one node table."""
    import numpy as np

    x = np.asarray(x).reshape(-1)
    neigh_list = [np.asarray(n).reshape(-1) for n in neighbors]
    count_list = [np.asarray(c).reshape(-1) for c in count]
    nodes = list(dict.fromkeys(
        x.tolist() + [int(v) for n in neigh_list for v in n]))
    lut = {int(n): i for i, n in enumerate(nodes)}
    reindex_src = np.concatenate(
        [np.asarray([lut[int(v)] for v in n], np.int64)
         for n in neigh_list]) if neigh_list else np.zeros(0, np.int64)
    reindex_dst = np.concatenate(
        [np.repeat(np.arange(len(x), dtype=np.int64), c)
         for c in count_list]) if count_list else np.zeros(0, np.int64)
    return reindex_src, reindex_dst, np.asarray(nodes, np.int64)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None):
    """ref: paddle.geometric.sample_neighbors (CSC graph) — one shared
    implementation with the incubate alias, including eids support."""
    from ..incubate import graph_sample_neighbors

    return graph_sample_neighbors(row, colptr, input_nodes, sample_size,
                                  eids, return_eids, perm_buffer)


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False):
    """ref: paddle.geometric.weighted_sample_neighbors — sampling
    probability proportional to edge weight."""
    import numpy as np

    from ..incubate import _rng

    row = np.asarray(row)
    colptr = np.asarray(colptr)
    w = np.asarray(edge_weight, np.float64)
    rng = _rng()
    out_neigh, out_count, out_eids = [], [], []
    eids_arr = None if eids is None else np.asarray(eids)
    for v in np.asarray(input_nodes).reshape(-1):
        lo, hi = int(colptr[v]), int(colptr[v + 1])
        pos = np.arange(lo, hi)
        wv = w[lo:hi]
        if sample_size >= 0 and len(pos) > sample_size:
            if wv.sum() > 0:
                p = wv / wv.sum()
                # replace=False cannot draw more than the positive-weight
                # support; cap like the reference's kernel does
                k = min(sample_size, int((wv > 0).sum()))
            else:
                p, k = None, sample_size
            pos = pos[rng.choice(len(pos), k, replace=False, p=p)]
        out_neigh.extend(row[pos].tolist())
        out_count.append(len(pos))
        if return_eids:
            chosen = (eids_arr[pos] if eids_arr is not None else pos)
            out_eids.extend(np.asarray(chosen).tolist())
    result = (np.asarray(out_neigh, np.int64),
              np.asarray(out_count, np.int64))
    if return_eids:
        return result + (np.asarray(out_eids, np.int64),)
    return result
