"""paddle_tpu.sparse.nn (ref: python/paddle/sparse/nn).

Activations act on the nonzero values in place (sparsity preserved).
The 3-D convolutions lower to dense XLA convs and re-sparsify:
`SubmConv3D` keeps the input's sparsity pattern (submanifold semantics —
exactly what the reference kernel guarantees), `Conv3D` re-derives the
output pattern from the dense result. On TPU the dense conv IS the fast
path (MXU wants dense tiles); the sparse formats save HBM at the
boundaries, which is where the reference's win on point clouds lives
too.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...nn.layer.base import Layer
from .. import (SparseCooTensor, SparseCsrTensor, _map_values, dense_to_coo,
                to_dense)
from . import functional  # noqa: F401


class ReLU(Layer):
    def forward(self, x):
        return functional.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return functional.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return functional.leaky_relu(x, self.negative_slope)


class Softmax(Layer):
    """Per-row softmax over the stored nonzeros (ref:
    sparse/nn/layer/activation.py::Softmax, axis=-1 only)."""

    def __init__(self, axis=-1, name=None):
        super().__init__()
        if axis != -1:
            raise ValueError('sparse Softmax supports axis=-1 only '
                             '(like the reference)')

    def forward(self, x):
        return functional.softmax(x)


class BatchNorm(Layer):
    """BatchNorm over the channel (last) axis of COO values
    (ref: sparse/nn/layer/norm.py::BatchNorm)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format='NDHWC',
                 name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum, self.epsilon = momentum, epsilon
        from ...nn import initializer as I

        self.weight = self.create_parameter(
            (num_features,), initializer=I.Constant(1.0))
        self.bias = self.create_parameter((num_features,), is_bias=True)
        self.register_buffer('_mean', jnp.zeros((num_features,)))
        self.register_buffer('_variance', jnp.ones((num_features,)))

    def forward(self, x):
        vals = x.values if isinstance(x, SparseCooTensor) else jnp.asarray(x)
        if self.training:
            mean = jnp.mean(vals, axis=0)
            var = jnp.var(vals, axis=0)
            # fold into the running stats like the dense BatchNorm —
            # including its unbiased-variance correction (norm.py)
            n = vals.shape[0]
            unbiased = var * n / max(n - 1, 1)
            m = self.momentum
            object.__setattr__(self, '_mean',
                               m * self._mean + (1 - m) * mean)
            object.__setattr__(self, '_variance',
                               m * self._variance + (1 - m) * unbiased)
        else:
            mean, var = self._mean, self._variance
        out = ((vals - mean) / jnp.sqrt(var + self.epsilon)
               * self.weight + self.bias)
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(x.indices, out, x.shape)
        return out


class Conv3D(Layer):
    """Sparse 3-D conv via dense lowering (ref: sparse/nn/layer/conv.py::
    Conv3D; NDHWC, channels last)."""

    SUBM = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode='zeros',
                 weight_attr=None, bias_attr=None, data_format='NDHWC'):
        super().__init__()
        if data_format != 'NDHWC':
            raise ValueError('sparse conv is NDHWC (like the reference)')
        from ...nn.layer.conv import Conv3D as DenseConv3D

        self._conv = DenseConv3D(in_channels, out_channels, kernel_size,
                                 stride=stride, padding=padding,
                                 dilation=dilation, groups=groups,
                                 data_format='NDHWC')

    def forward(self, x):
        dense = to_dense(x) if isinstance(x, SparseCooTensor) else x
        out = self._conv(dense)
        if not isinstance(x, SparseCooTensor):
            return out
        if self.SUBM:
            # submanifold: output pattern == input pattern — gather the
            # dense result at the input's active sites
            vals = out[tuple(x.indices)]        # (nnz, C_out)
            return SparseCooTensor(x.indices, vals, out.shape)
        return _site_coo(out)


class SubmConv3D(Conv3D):
    SUBM = True


class MaxPool3D(Layer):
    """ref: sparse/nn/layer/pooling.py::MaxPool3D (NDHWC, dense-lowered)."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format='NDHWC', name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride or kernel_size
        self.padding = padding

    def forward(self, x):
        from ...nn import functional as F

        dense = to_dense(x) if isinstance(x, SparseCooTensor) else x
        out = F.max_pool3d(dense, self.kernel_size, self.stride,
                           self.padding, data_format='NDHWC')
        return dense_to_coo(out) if isinstance(x, SparseCooTensor) else out


def _site_coo(dense):
    """Channels-last dense -> site-based COO: indices over the spatial
    dims, values carry the channel vector (eager host nnz discovery)."""
    import numpy as np

    arr = np.asarray(dense)
    sites = np.nonzero(np.any(arr != 0, axis=-1))
    idx = jnp.asarray(np.stack(sites))
    vals = dense[tuple(idx)]
    return SparseCooTensor(idx, vals, dense.shape)
