"""sparse.nn.functional (ref: python/paddle/sparse/nn/functional)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import SparseCooTensor, SparseCsrTensor, _map_values


def relu(x):
    return _map_values(lambda v: jnp.maximum(v, 0), x)


def relu6(x):
    return _map_values(lambda v: jnp.clip(v, 0, 6), x)


def leaky_relu(x, negative_slope=0.01):
    return _map_values(
        lambda v: jnp.where(v >= 0, v, negative_slope * v), x)


def softmax(x, axis=-1):
    """Per-row softmax over stored nonzeros (ref: sparse/nn/functional/
    activation.py::softmax; CSR rows, or COO last sparse dim)."""
    if axis != -1:
        raise ValueError('sparse softmax supports axis=-1 only')
    if isinstance(x, SparseCsrTensor):
        rows = x._row_ids()
        vmax = jnp.full((x.shape[0],), -jnp.inf, jnp.float32).at[rows].max(
            x.values.astype(jnp.float32))
        e = jnp.exp(x.values.astype(jnp.float32) - vmax[rows])
        denom = jnp.zeros((x.shape[0],), jnp.float32).at[rows].add(e)
        return SparseCsrTensor(x.crows, x.cols, (e / denom[rows]).astype(
            x.values.dtype), x.shape)
    if isinstance(x, SparseCooTensor):
        # group by all-but-last sparse index
        lead = x.indices[:-1]
        flat = jnp.ravel_multi_index(
            tuple(lead), x.shape[:lead.shape[0]], mode='clip') \
            if lead.shape[0] else jnp.zeros(x.nnz(), jnp.int32)
        n_rows = 1
        for s in x.shape[:lead.shape[0]]:
            n_rows *= s
        v = x.values.astype(jnp.float32)
        vmax = jnp.full((n_rows,), -jnp.inf, jnp.float32).at[flat].max(v)
        e = jnp.exp(v - vmax[flat])
        denom = jnp.zeros((n_rows,), jnp.float32).at[flat].add(e)
        return SparseCooTensor(x.indices, (e / denom[flat]).astype(
            x.values.dtype), x.shape)
    return jax.nn.softmax(jnp.asarray(x), axis=axis)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None):
    """CSR-masked attention (ref: sparse/nn/functional/transformer.py::
    attention) — delegates to the dense-fused sparse_attention path."""
    from ...nn.functional.attention import sparse_attention as _sa

    b, h, s, _ = query.shape
    crows = jnp.broadcast_to(sparse_mask.crows, (b, h, s + 1))
    cols = jnp.broadcast_to(sparse_mask.cols, (b, h, sparse_mask.nnz()))
    return _sa(query, key, value, crows, cols,
               key_padding_mask=key_padding_mask, attn_mask=attn_mask)
