"""Sparse namespace — COO basics (ref: python/paddle/sparse).

TPU-native: COO is (indices, values, shape); matmul/reductions lower to
dense segment ops (`.at[].add`), which XLA scatters efficiently. Dense
fallbacks are correct at any sparsity; the TPU win is memory, not
FLOPs, since the MXU wants dense tiles.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class SparseCooTensor:
    """ref: paddle.sparse.sparse_coo_tensor return type."""

    def __init__(self, indices, values, shape, coalesced=False):
        self.indices = jnp.asarray(indices)      # (ndim, nnz)
        self.values = jnp.asarray(values)        # (nnz, ...)
        self.shape = tuple(shape)
        self._coalesced = coalesced

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def ndim(self):
        return len(self.shape)

    def nnz(self):
        return self.values.shape[0]

    def to_dense(self):
        # `shape` may be the sparse dims only, or (paddle-style) already
        # include the values' trailing dense dims — detect which
        if len(self.shape) == self.indices.shape[0] + self.values.ndim - 1:
            dense = jnp.zeros(self.shape, self.values.dtype)
        else:
            dense = jnp.zeros(self.shape + self.values.shape[1:],
                              self.values.dtype)
        return dense.at[tuple(self.indices)].add(self.values)

    def coalesce(self):
        flat = jnp.ravel_multi_index(tuple(self.indices),
                                     self.shape[:self.indices.shape[0]],
                                     mode='clip')
        order = jnp.argsort(flat)
        sorted_flat = flat[order]
        sorted_vals = self.values[order]
        unique, inv = jnp.unique(sorted_flat, return_inverse=True,
                                 size=flat.shape[0], fill_value=-1)
        summed = jnp.zeros_like(sorted_vals).at[inv].add(sorted_vals)
        keep = unique >= 0
        idx = jnp.stack(jnp.unravel_index(jnp.maximum(unique, 0), self.shape))
        return SparseCooTensor(idx, jnp.where(keep[..., None] if summed.ndim > 1
                                              else keep, summed, 0),
                               self.shape, coalesced=True)

    def __repr__(self):
        return (f'SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, '
                f'dtype={self.dtype})')


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """ref: paddle.sparse.sparse_coo_tensor."""
    indices = jnp.asarray(indices)
    values = jnp.asarray(values, dtype)
    if shape is None:
        shape = tuple(int(i) + 1 for i in np.asarray(indices.max(axis=1)))
    return SparseCooTensor(indices, values, shape)


def to_dense(x):
    return x.to_dense() if isinstance(x, SparseCooTensor) else x


def is_sparse_coo(x):
    return isinstance(x, SparseCooTensor)


def matmul(a, b):
    """Sparse @ dense (ref: paddle.sparse.matmul) via gather+segment-add."""
    if isinstance(a, SparseCooTensor):
        assert a.ndim == 2, '2-D sparse matmul'
        b = jnp.asarray(b)
        rows, cols = a.indices
        contrib = a.values[:, None] * b[cols]        # (nnz, N)
        out = jnp.zeros((a.shape[0], b.shape[1]), contrib.dtype)
        return out.at[rows].add(contrib)
    if isinstance(b, SparseCooTensor):
        return matmul(b.transpose(), jnp.asarray(a).T).T
    return jnp.asarray(a) @ jnp.asarray(b)


def add(a, b):
    if isinstance(a, SparseCooTensor) and isinstance(b, SparseCooTensor):
        assert a.shape == b.shape
        return SparseCooTensor(
            jnp.concatenate([a.indices, b.indices], axis=1),
            jnp.concatenate([a.values, b.values]), a.shape)
    return to_dense(a) + to_dense(b)


def relu(x):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices, jnp.maximum(x.values, 0), x.shape)
    return jnp.maximum(x, 0)


def transpose(x, perm=(1, 0)):
    if isinstance(x, SparseCooTensor):
        new_idx = x.indices[jnp.asarray(perm)]
        new_shape = tuple(x.shape[p] for p in perm)
        return SparseCooTensor(new_idx, x.values, new_shape)
    return jnp.transpose(x, perm)


SparseCooTensor.transpose = lambda self, perm=(1, 0): transpose(self, perm)


class SparseCsrTensor:
    """CSR format (ref: paddle.sparse.sparse_csr_tensor return type):
    (crows, cols, values, shape). 2-D (or batched 3-D) only, like the
    reference."""

    def __init__(self, crows, cols, values, shape):
        self.crows = jnp.asarray(crows)
        self.cols = jnp.asarray(cols)
        self.values = jnp.asarray(values)
        self.shape = tuple(shape)

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def ndim(self):
        return len(self.shape)

    def nnz(self):
        return self.values.shape[0]

    def _row_ids(self):
        # nnz -> owning row, from the compressed row pointer
        return jnp.searchsorted(self.crows, jnp.arange(self.nnz()),
                                side='right') - 1

    def to_dense(self):
        rows = self._row_ids()
        dense = jnp.zeros(self.shape, self.values.dtype)
        return dense.at[rows, self.cols].add(self.values)

    def to_sparse_coo(self, sparse_dim=2):
        return SparseCooTensor(
            jnp.stack([self._row_ids(), self.cols]), self.values, self.shape)

    def __repr__(self):
        return (f'SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, '
                f'dtype={self.dtype})')


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    """ref: paddle.sparse.sparse_csr_tensor."""
    return SparseCsrTensor(crows, cols, jnp.asarray(values, dtype), shape)


def _is_sparse(x):
    return isinstance(x, (SparseCooTensor, SparseCsrTensor))


def is_sparse_csr(x):
    return isinstance(x, SparseCsrTensor)


def _map_values(fn, x):
    """Apply an elementwise op to the nonzero values, keeping sparsity.
    (Only zero-preserving ops are exposed this way, matching the
    reference's sparse unary API.)"""
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices, fn(x.values), x.shape)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x.crows, x.cols, fn(x.values), x.shape)
    return fn(jnp.asarray(x))


def sin(x): return _map_values(jnp.sin, x)
def tan(x): return _map_values(jnp.tan, x)
def asin(x): return _map_values(jnp.arcsin, x)
def atan(x): return _map_values(jnp.arctan, x)
def sinh(x): return _map_values(jnp.sinh, x)
def tanh(x): return _map_values(jnp.tanh, x)
def asinh(x): return _map_values(jnp.arcsinh, x)
def atanh(x): return _map_values(jnp.arctanh, x)
def sqrt(x): return _map_values(jnp.sqrt, x)
def square(x): return _map_values(jnp.square, x)
def log1p(x): return _map_values(jnp.log1p, x)
def abs(x): return _map_values(jnp.abs, x)
def neg(x): return _map_values(jnp.negative, x)
def expm1(x): return _map_values(jnp.expm1, x)
def deg2rad(x): return _map_values(jnp.deg2rad, x)
def rad2deg(x): return _map_values(jnp.rad2deg, x)
def isnan(x): return _map_values(jnp.isnan, x)


def pow(x, factor):
    return _map_values(lambda v: jnp.power(v, factor), x)


def cast(x, index_dtype=None, value_dtype=None):
    def conv(v):
        return v.astype(value_dtype) if value_dtype else v
    out = _map_values(conv, x)
    if index_dtype and isinstance(out, SparseCooTensor):
        out = SparseCooTensor(out.indices.astype(index_dtype), out.values,
                              out.shape)
    if index_dtype and isinstance(out, SparseCsrTensor):
        out = SparseCsrTensor(out.crows.astype(index_dtype),
                              out.cols.astype(index_dtype), out.values,
                              out.shape)
    return out


def _binary(fn, a, b):
    """Elementwise binary on matching-sparsity operands; general case
    lowers to dense (documented TPU trade: see module docstring)."""
    if isinstance(a, SparseCooTensor) and isinstance(b, SparseCooTensor):
        ac, bc = a.coalesce(), b.coalesce()
        if (ac.indices.shape == bc.indices.shape
                and bool(jnp.all(ac.indices == bc.indices))):
            return SparseCooTensor(ac.indices, fn(ac.values, bc.values),
                                   ac.shape)
    if isinstance(a, SparseCsrTensor) and isinstance(b, SparseCsrTensor):
        if (a.cols.shape == b.cols.shape
                and bool(jnp.all(a.cols == b.cols))
                and bool(jnp.all(a.crows == b.crows))):
            return SparseCsrTensor(a.crows, a.cols, fn(a.values, b.values),
                                   a.shape)
    return fn(to_dense(a), to_dense(b))


def subtract(a, b):
    return _binary(jnp.subtract, a, b)


def multiply(a, b):
    return _binary(jnp.multiply, a, b)


def divide(a, b):
    return _binary(jnp.divide, a, b)


def coalesce(x):
    return x.coalesce() if isinstance(x, SparseCooTensor) else x


def is_same_shape(x, y):
    xs = x.shape if hasattr(x, 'shape') else ()
    ys = y.shape if hasattr(y, 'shape') else ()
    return tuple(xs) == tuple(ys)


def reshape(x, shape):
    """ref: paddle.sparse.reshape — recompute COO indices for the new
    shape (same linearization)."""
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    if not isinstance(x, SparseCooTensor):
        return jnp.reshape(jnp.asarray(x), shape)
    shape = list(shape)
    n_elem = 1
    for s in x.shape:
        n_elem *= s
    neg = [i for i, s in enumerate(shape) if s == -1]
    if neg:
        known = 1
        for s in shape:
            if s != -1:
                known *= s
        shape[neg[0]] = n_elem // known
    flat = jnp.ravel_multi_index(tuple(x.indices), x.shape, mode='clip')
    new_idx = jnp.stack(jnp.unravel_index(flat, tuple(shape)))
    return SparseCooTensor(new_idx, x.values, tuple(shape))


def slice(x, axes, starts, ends):
    """ref: paddle.sparse.slice — dense-lowered gather then re-sparsify."""
    import builtins

    dense = to_dense(x)
    sl = [builtins.slice(None)] * dense.ndim
    for ax, st, en in zip(axes, starts, ends):
        sl[ax] = builtins.slice(st, en)
    out = dense[tuple(sl)]
    if isinstance(x, SparseCooTensor):
        return dense_to_coo(out)
    if isinstance(x, SparseCsrTensor):
        return dense_to_csr(out)
    return out


def dense_to_coo(x, sparse_dim=None):
    """Eager densifier inverse (host-side nnz discovery — eager only,
    like the reference's Tensor.to_sparse_coo)."""
    x = jnp.asarray(x)
    nz = np.nonzero(np.asarray(x))
    idx = jnp.asarray(np.stack(nz))
    vals = x[tuple(idx)]
    return SparseCooTensor(idx, vals, x.shape)


def dense_to_csr(x):
    x = jnp.asarray(x)
    assert x.ndim == 2, 'CSR is 2-D'
    xn = np.asarray(x)
    rows, cols = np.nonzero(xn)
    crows = np.zeros(x.shape[0] + 1, np.int64)
    np.add.at(crows, rows + 1, 1)
    crows = np.cumsum(crows)
    return SparseCsrTensor(jnp.asarray(crows), jnp.asarray(cols),
                           x[rows, cols], x.shape)


def mv(a, vec):
    """Sparse matrix @ dense vector (ref: paddle.sparse.mv)."""
    vec = jnp.asarray(vec)
    return matmul(a, vec[:, None])[:, 0]


def addmm(input, x, y, beta=1.0, alpha=1.0):
    """beta * input + alpha * (x @ y) (ref: paddle.sparse.addmm)."""
    return beta * to_dense(input) + alpha * matmul(x, to_dense(y))


def masked_matmul(x, y, mask):
    """Dense @ dense, evaluated only at `mask`'s sparsity pattern
    (ref: paddle.sparse.masked_matmul — SDDMM). The gather-dot form
    computes just the nnz dot products, not the full product."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if isinstance(mask, SparseCsrTensor):
        rows, cols = mask._row_ids(), mask.cols
        vals = jnp.einsum('nk,nk->n', x[rows], y[:, cols].T)
        return SparseCsrTensor(mask.crows, mask.cols, vals, mask.shape)
    rows, cols = mask.indices
    vals = jnp.einsum('nk,nk->n', x[rows], y[:, cols].T)
    return SparseCooTensor(mask.indices, vals, mask.shape)


def mask_as(x, mask):
    """Keep x's entries at mask's sparsity pattern
    (ref: paddle.sparse.mask_as)."""
    dense = jnp.asarray(to_dense(x))
    if isinstance(mask, SparseCooTensor):
        vals = dense[tuple(mask.indices)]
        return SparseCooTensor(mask.indices, vals, mask.shape)
    rows, cols = mask._row_ids(), mask.cols
    return SparseCsrTensor(mask.crows, mask.cols, dense[rows, cols],
                           mask.shape)


def sum(x, axis=None, dtype=None, keepdim=False):
    """ref: paddle.sparse.sum — over values (axis=None) or dense-lowered."""
    if axis is None:
        out = jnp.sum(x.values if _is_sparse(x) else jnp.asarray(x))
        return out.astype(dtype) if dtype else out
    dense = to_dense(x)
    out = jnp.sum(dense, axis=axis, keepdims=keepdim)
    if dtype:
        out = out.astype(dtype)
    if isinstance(x, SparseCooTensor) and not keepdim:
        return dense_to_coo(out) if out.ndim else out
    return out


def pca_lowrank(x, q=None, center=True, niter=2):
    """ref: paddle.sparse.pca_lowrank — dense lowering into linalg."""
    from ..tensor.linalg import pca_lowrank as dense_pca

    return dense_pca(to_dense(x), q=q, center=center, niter=niter)


from . import nn  # noqa: E402,F401
