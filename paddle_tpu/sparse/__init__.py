"""Sparse namespace — COO basics (ref: python/paddle/sparse).

TPU-native: COO is (indices, values, shape); matmul/reductions lower to
dense segment ops (`.at[].add`), which XLA scatters efficiently. Dense
fallbacks are correct at any sparsity; the TPU win is memory, not
FLOPs, since the MXU wants dense tiles.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class SparseCooTensor:
    """ref: paddle.sparse.sparse_coo_tensor return type."""

    def __init__(self, indices, values, shape, coalesced=False):
        self.indices = jnp.asarray(indices)      # (ndim, nnz)
        self.values = jnp.asarray(values)        # (nnz, ...)
        self.shape = tuple(shape)
        self._coalesced = coalesced

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def ndim(self):
        return len(self.shape)

    def nnz(self):
        return self.values.shape[0]

    def to_dense(self):
        dense = jnp.zeros(self.shape + self.values.shape[1:], self.values.dtype)
        return dense.at[tuple(self.indices)].add(self.values)

    def coalesce(self):
        flat = jnp.ravel_multi_index(tuple(self.indices),
                                     self.shape[:self.indices.shape[0]],
                                     mode='clip')
        order = jnp.argsort(flat)
        sorted_flat = flat[order]
        sorted_vals = self.values[order]
        unique, inv = jnp.unique(sorted_flat, return_inverse=True,
                                 size=flat.shape[0], fill_value=-1)
        summed = jnp.zeros_like(sorted_vals).at[inv].add(sorted_vals)
        keep = unique >= 0
        idx = jnp.stack(jnp.unravel_index(jnp.maximum(unique, 0), self.shape))
        return SparseCooTensor(idx, jnp.where(keep[..., None] if summed.ndim > 1
                                              else keep, summed, 0),
                               self.shape, coalesced=True)

    def __repr__(self):
        return (f'SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, '
                f'dtype={self.dtype})')


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """ref: paddle.sparse.sparse_coo_tensor."""
    indices = jnp.asarray(indices)
    values = jnp.asarray(values, dtype)
    if shape is None:
        shape = tuple(int(i) + 1 for i in np.asarray(indices.max(axis=1)))
    return SparseCooTensor(indices, values, shape)


def to_dense(x):
    return x.to_dense() if isinstance(x, SparseCooTensor) else x


def is_sparse_coo(x):
    return isinstance(x, SparseCooTensor)


def matmul(a, b):
    """Sparse @ dense (ref: paddle.sparse.matmul) via gather+segment-add."""
    if isinstance(a, SparseCooTensor):
        assert a.ndim == 2, '2-D sparse matmul'
        b = jnp.asarray(b)
        rows, cols = a.indices
        contrib = a.values[:, None] * b[cols]        # (nnz, N)
        out = jnp.zeros((a.shape[0], b.shape[1]), contrib.dtype)
        return out.at[rows].add(contrib)
    if isinstance(b, SparseCooTensor):
        return matmul(b.transpose(), jnp.asarray(a).T).T
    return jnp.asarray(a) @ jnp.asarray(b)


def add(a, b):
    if isinstance(a, SparseCooTensor) and isinstance(b, SparseCooTensor):
        assert a.shape == b.shape
        return SparseCooTensor(
            jnp.concatenate([a.indices, b.indices], axis=1),
            jnp.concatenate([a.values, b.values]), a.shape)
    return to_dense(a) + to_dense(b)


def relu(x):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices, jnp.maximum(x.values, 0), x.shape)
    return jnp.maximum(x, 0)


def transpose(x, perm=(1, 0)):
    if isinstance(x, SparseCooTensor):
        new_idx = x.indices[jnp.asarray(perm)]
        new_shape = tuple(x.shape[p] for p in perm)
        return SparseCooTensor(new_idx, x.values, new_shape)
    return jnp.transpose(x, perm)


SparseCooTensor.transpose = lambda self, perm=(1, 0): transpose(self, perm)
