"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capabilities, built from scratch on jax/XLA/pallas.

Architecture (see SURVEY.md):
  - models are jax pytrees (`nn.Layer`) — jit/grad/pjit work on them directly
  - ops lower to XLA HLO; hot paths use pallas TPU kernels (`ops/`)
  - distributed = `jax.sharding.Mesh` + GSPMD specs (`distributed/`),
    replacing Fleet's NCCL process groups with ICI collectives
"""
from __future__ import annotations

__version__ = '0.1.0'

from .framework import dtype as _dtype_mod
from .framework.dtype import (  # noqa: F401
    bfloat16,
    bool_,
    complex64,
    complex128,
    finfo,
    float16,
    float32,
    float64,
    get_default_dtype,
    iinfo,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .framework.random import get_rng_state, seed, set_rng_state  # noqa: F401
from .tensor import *  # noqa: F401,F403
from .tensor import Tensor  # noqa: F401
from . import tensor  # noqa: F401
from . import autograd  # noqa: F401
from .autograd import grad, no_grad, value_and_grad  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import framework  # noqa: F401
from . import device  # noqa: F401
from .device import CPUPlace, TPUPlace, get_device, set_device  # noqa: F401
from .device import (  # noqa: F401
    get_cudnn_version,
    is_compiled_with_cinn,
    is_compiled_with_cuda,
    is_compiled_with_custom_device,
    is_compiled_with_distribute,
    is_compiled_with_ipu,
    is_compiled_with_rocm,
    is_compiled_with_tpu,
    is_compiled_with_xpu,
)
from . import jit  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import linalg  # noqa: F401
from . import distributed  # noqa: F401
from . import models  # noqa: F401
from . import metric  # noqa: F401
from . import callbacks  # noqa: F401
from . import hapi  # noqa: F401
from .hapi import Model, summary  # noqa: F401
from . import audio  # noqa: F401
from . import distribution  # noqa: F401
from . import fft  # noqa: F401
from . import geometric  # noqa: F401
from . import incubate  # noqa: F401
from . import dataset  # noqa: F401
from . import hub  # noqa: F401
from . import inference  # noqa: F401
from . import testing  # noqa: F401
from . import training  # noqa: F401
from . import aot  # noqa: F401
from . import onnx  # noqa: F401
from . import reader  # noqa: F401
from . import sysconfig  # noqa: F401
from . import version  # noqa: F401
from . import signal  # noqa: F401
from . import text  # noqa: F401
from . import sparse  # noqa: F401
from . import utils  # noqa: F401
from . import vision  # noqa: F401
from . import profiler  # noqa: F401
from . import observability  # noqa: F401
from .framework.flags import get_flags, set_flags  # noqa: F401
from .utils.flops import flops  # noqa: F401
from . import static  # noqa: F401
from . import quantization  # noqa: F401
from . import regularizer  # noqa: F401
from .distributed.parallel import DataParallel  # noqa: F401
from .framework.io import load, save  # noqa: F401

import jax.numpy as _jnp

# dtype checks on arrays
def is_floating_point(x):
    return _dtype_mod.is_floating_point(x.dtype if hasattr(x, 'dtype') else x)


def is_complex(x):
    import numpy as _np

    return _np.issubdtype(x.dtype, _np.complexfloating)


def is_integer(x):
    return _dtype_mod.is_integer(x.dtype if hasattr(x, 'dtype') else x)


# ---- top-level long tail (ref: python/paddle/__init__.py __all__) ----------
from .tensor import extension as _ext  # noqa: E402
from .tensor.extension import *  # noqa: F401,F403,E402
from .tensor.extension import rank, shape, tolist  # noqa: F401,E402
from .tensor.random import (  # noqa: F401,E402
    binomial,
    cauchy_,
    geometric_,
    log_normal,
    log_normal_,
)
from .framework import compat as _compat  # noqa: E402
from .framework.compat import (  # noqa: F401,E402
    LazyGuard,
    ParamAttr,
    batch,
    check_shape,
    create_parameter,
    disable_signal_handler,
    disable_static,
    enable_static,
    get_cuda_rng_state,
    in_dynamic_mode,
    set_cuda_rng_state,
    set_grad_enabled,
    set_printoptions,
)
from .autograd import enable_grad, is_grad_enabled  # noqa: F401,E402
from .device import CPUPlace as CUDAPinnedPlace  # noqa: F401,E402
from .device import TPUPlace as CUDAPlace  # noqa: F401,E402
from .framework.dtype import bool_ as bool  # noqa: F401,E402,A001
from .framework.dtype import float8_e4m3 as float8_e4m3fn  # noqa: F401,E402
from .framework.dtype import float8_e5m2  # noqa: F401,E402

dtype = _jnp.dtype  # paddle.dtype: the dtype type itself

# In-place variants: jax arrays are immutable, so each `op_` is the pure
# op — reference code uses the return value, which matches.
import sys as _sys  # noqa: E402

_self = _sys.modules[__name__]
for _name in [
    'abs', 'acos', 'addmm', 'asin', 'atan', 'atan2', 'bitwise_and',
    'bitwise_not', 'bitwise_or', 'bitwise_xor', 'cast', 'ceil', 'clip',
    'copysign', 'cos', 'cumprod', 'cumsum', 'digamma', 'divide', 'equal',
    'erf', 'erfinv', 'exp', 'expm1', 'fill_diagonal', 'flatten', 'floor',
    'floor_divide', 'floor_mod', 'frac', 'gammainc', 'gammaincc',
    'gammaln', 'gcd', 'greater_equal', 'greater_than', 'hardtanh',
    'hypot', 'i0', 'index_add', 'index_fill', 'index_put', 'lcm',
    'ldexp', 'less_equal', 'less_than', 'lerp', 'lgamma', 'log', 'log10',
    'log1p', 'log2', 'logical_and', 'logical_not', 'logical_or',
    'logical_xor', 'logit', 'masked_fill', 'masked_scatter', 'mod',
    'multigammaln', 'multiply', 'nan_to_num', 'neg', 'normal', 'pow',
    'polygamma', 'put_along_axis', 'reciprocal', 'remainder', 'renorm',
    'round', 'rsqrt', 'scale', 'scatter', 'sigmoid', 'sin', 'sinc',
    'sinh', 'sqrt', 'square', 'squeeze', 'subtract', 't', 'tan', 'tanh',
    'tril', 'triu', 'trunc', 'uniform', 'unsqueeze', 'where', 'zero',
    'bitwise_left_shift', 'bitwise_right_shift', 'exponential',
    'bernoulli', 'transpose',
]:
    _fn = getattr(_self, _name, None)
    if _fn is not None and not hasattr(_self, _name + '_'):
        setattr(_self, _name + '_', _fn)
del _sys, _self, _name, _fn

# Bind the paddle Tensor method surface (x.unsqueeze / x.numpy / x.add ...)
# onto jax array + tracer classes — ref tensor/__init__.py:459,
# base/dygraph/tensor_patch_methods.py:86. Must run after the namespaces
# above exist.
from .tensor import methods as _tensor_methods  # noqa: E402

_tensor_methods.monkey_patch_tensor()
del _tensor_methods
