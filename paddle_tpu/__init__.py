"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capabilities, built from scratch on jax/XLA/pallas.

Architecture (see SURVEY.md):
  - models are jax pytrees (`nn.Layer`) — jit/grad/pjit work on them directly
  - ops lower to XLA HLO; hot paths use pallas TPU kernels (`ops/`)
  - distributed = `jax.sharding.Mesh` + GSPMD specs (`distributed/`),
    replacing Fleet's NCCL process groups with ICI collectives
"""
from __future__ import annotations

__version__ = '0.1.0'

from .framework import dtype as _dtype_mod
from .framework.dtype import (  # noqa: F401
    bfloat16,
    bool_,
    complex64,
    complex128,
    finfo,
    float16,
    float32,
    float64,
    get_default_dtype,
    iinfo,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .framework.random import get_rng_state, seed, set_rng_state  # noqa: F401
from .tensor import *  # noqa: F401,F403
from .tensor import Tensor  # noqa: F401
from . import tensor  # noqa: F401
from . import autograd  # noqa: F401
from .autograd import grad, no_grad, value_and_grad  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import framework  # noqa: F401
from . import device  # noqa: F401
from .device import CPUPlace, TPUPlace, get_device, set_device  # noqa: F401
from . import jit  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import linalg  # noqa: F401
from . import distributed  # noqa: F401
from . import models  # noqa: F401
from . import metric  # noqa: F401
from . import callbacks  # noqa: F401
from . import hapi  # noqa: F401
from .hapi import Model, summary  # noqa: F401
from . import audio  # noqa: F401
from . import distribution  # noqa: F401
from . import fft  # noqa: F401
from . import geometric  # noqa: F401
from . import incubate  # noqa: F401
from . import signal  # noqa: F401
from . import sparse  # noqa: F401
from . import utils  # noqa: F401
from . import vision  # noqa: F401
from . import profiler  # noqa: F401
from .framework.flags import get_flags, set_flags  # noqa: F401
from .utils.flops import flops  # noqa: F401
from . import static  # noqa: F401
from . import quantization  # noqa: F401
from . import regularizer  # noqa: F401
from .distributed.parallel import DataParallel  # noqa: F401
from .framework.io import load, save  # noqa: F401

import jax.numpy as _jnp

# dtype checks on arrays
def is_floating_point(x):
    return _dtype_mod.is_floating_point(x.dtype if hasattr(x, 'dtype') else x)


def is_complex(x):
    import numpy as _np

    return _np.issubdtype(x.dtype, _np.complexfloating)


def is_integer(x):
    return _dtype_mod.is_integer(x.dtype if hasattr(x, 'dtype') else x)
