"""SLO watchdog — declarative health rules over the windowed
timeseries, with a machine-readable verdict.

The flight recorder (PR 12) answers "what happened to request 1742";
this module answers the question a supervisor, router, or autoscaler
asks every second: "is this engine healthy RIGHT NOW — yes or no, and
if no, which contract is it breaking?" The shape is the SRE standard:
declarative rules over windowed metrics with hysteresis, evaluated at
window-commit granularity, breach/recovery EDGES journaled and
counted, verdict served by `/healthz` (httpd.py).

An `SLORule` is one inequality over one windowed expression:

    SLORule('ttft_p99', 'p99(serve.ttft_ms)', '>', 500.0,
            for_windows=3, clear_windows=2)

Expression forms (all evaluated against ONE committed window, plus
the ring for rolling forms):

    rate(counter)      per-second rate of the window's counter delta
    delta(counter)     the window's counter (or histogram-count) delta
    gauge(name)        gauge value as of the window  (alias: value)
    counter(name)      CUMULATIVE counter value (since boot)
    p50/p95/p99(hist)  the window's interpolated percentile over the
                       histogram's bucket DELTAS
    mean(hist)         the window's mean observation
    ratio(a, b)        delta(a) / delta(b); no-data when delta(b) == 0

An expression that resolves to None — metric absent, empty window,
zero denominator — is NO DATA: the rule reports 'no_data', its
true-streak resets (missing evidence never pages), and an active
breach is held until `clear_windows` consecutive HEALTHY windows
actually clear it.

Hysteresis: a rule breaches only after its condition holds for
`for_windows` CONSECUTIVE windows, and recovers only after it fails
for `clear_windows` consecutive windows — single-window blips neither
page nor flap a recovery. Both edges journal a structured event
(`slo_breach` / `slo_recovered`, rule + value + threshold) and tick
`watchdog.breaches` / `watchdog.recoveries`; every evaluated window
ticks `watchdog.evaluations` and refreshes the `watchdog.healthy` /
`watchdog.breaching_rules` gauges.

A breach edge can additionally auto-dump a THROTTLED postmortem
bundle through the PR-12 crash path (`postmortem_engine=` an engine
with `postmortem_dir` set, `postmortem_min_interval_s` between
dumps) — the incident bundle exists before anyone ssh'es in.

Watchdog state is JSON-able (`snapshot_state()` / `load_state()`) and
rides `ServingEngine.snapshot()`/`restore()`, so a restored standby
continues the primary's health history: an active breach stays active
across the failover instead of silently re-arming.

Stdlib-only at import (no jax, no numpy), like the whole package.
"""
from __future__ import annotations

import re
import time

from . import journal as _journal
from . import metrics as _metrics

__all__ = ['SLORule', 'Watchdog', 'default_serving_rules']

_OPS = {
    '>': lambda a, b: a > b,
    '>=': lambda a, b: a >= b,
    '<': lambda a, b: a < b,
    '<=': lambda a, b: a <= b,
    '==': lambda a, b: a == b,
    '!=': lambda a, b: a != b,
}

_FNS = ('rate', 'delta', 'gauge', 'value', 'counter', 'mean',
        'p50', 'p95', 'p99', 'ratio')

_EXPR_RE = re.compile(
    r'^\s*(?P<fn>[a-z0-9]+)\s*\(\s*(?P<a>[\w./-]+)'
    r'\s*(?:,\s*(?P<b>[\w./-]+)\s*)?\)\s*$')


class SLORule:
    """One declarative SLO: windowed expression, comparison, threshold,
    hysteresis. Immutable config; the mutable evaluation state lives in
    the Watchdog so one ruleset object can serve many engines."""

    def __init__(self, name, expr, op, threshold, *, for_windows=1,
                 clear_windows=1, help=''):
        self.name = str(name)
        self.expr = str(expr)
        m = _EXPR_RE.match(self.expr)
        if not m or m.group('fn') not in _FNS:
            raise ValueError(
                f'rule {name!r}: unparseable expr {expr!r} — expected '
                f'fn(metric) with fn in {_FNS} (ratio takes two)')
        self._fn = m.group('fn')
        self._a = m.group('a')
        self._b = m.group('b')
        if (self._fn == 'ratio') != (self._b is not None):
            raise ValueError(
                f'rule {name!r}: ratio(a, b) takes exactly two metrics; '
                f'every other form takes one')
        if op not in _OPS:
            raise ValueError(
                f'rule {name!r}: op {op!r} not in {sorted(_OPS)}')
        self.op = op
        self.threshold = float(threshold)
        self.for_windows = int(for_windows)
        self.clear_windows = int(clear_windows)
        if self.for_windows < 1 or self.clear_windows < 1:
            raise ValueError(
                f'rule {name!r}: for_windows and clear_windows must '
                f'be >= 1')
        self.help = help

    def evaluate(self, window, ts=None):
        """The expression's value for one committed window (None = no
        data). `ts` (the WindowedTimeseries) backs the cumulative
        `counter()` form's registry read."""
        fn, a = self._fn, self._a

        def delta_of(name):
            # counter delta, or histogram observation-count delta —
            # the same resolution delta() uses, so ratio() really is
            # delta(a)/delta(b) for every metric kind that has one
            c = window['counters'].get(name)
            if c is not None:
                return c['delta']
            h = window['hists'].get(name)
            return h['count'] if h is not None else None

        if fn in ('rate', 'delta'):
            c = window['counters'].get(a)
            if c is not None:
                return c[fn]
            h = window['hists'].get(a)
            if h is not None:
                return h['rate'] if fn == 'rate' else h['count']
            return None
        if fn in ('gauge', 'value'):
            return window['gauges'].get(a)
        if fn == 'counter':
            reg = ts.registry if ts is not None else _metrics.REGISTRY
            m = reg.get(a)
            return m.value if m is not None and m.kind == 'counter' else None
        if fn == 'ratio':
            num = delta_of(a)
            den = delta_of(self._b)
            if num is None or den is None or den == 0:
                return None
            return num / den
        h = window['hists'].get(a)            # mean / p50 / p95 / p99
        return h[fn] if h is not None else None

    def config(self):
        return {'expr': self.expr, 'op': self.op,
                'threshold': self.threshold,
                'for_windows': self.for_windows,
                'clear_windows': self.clear_windows, 'help': self.help}


def _fresh_state():
    return {'state': 'ok', 'last': None, 'last_value': None,
            'true_streak': 0, 'false_streak': 0, 'breaches': 0,
            'recoveries': 0, 'breached_at_idx': None,
            'windows_evaluated': 0}


class Watchdog:
    """Evaluates a ruleset against each committed window and holds the
    per-rule breach state machine. `verdict()` is the machine-readable
    health answer `/healthz` serves."""

    def __init__(self, rules, *, postmortem_engine=None,
                 postmortem_min_interval_s=300.0, on_breach=None,
                 on_recover=None, registry=None, journal=None):
        # where the watchdog.* counters/gauges and the slo_breach /
        # slo_recovered journal events land. None = the process
        # globals (prior behavior); a private-registry replica passes
        # its own scopes so N in-process replicas' health series never
        # merge (the fleet routes off per-replica verdicts).
        self.registry = registry
        self.journal = journal
        self.rules = list(rules)
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f'duplicate rule names in {names}')
        self._state = {r.name: _fresh_state() for r in self.rules}
        self.windows_evaluated = 0
        self.breaches_total = 0
        self.recoveries_total = 0
        self.last_window_idx = None
        # throttled auto-postmortem through the PR-12 crash path: the
        # engine's own `_auto_postmortem` (bundle + journal event +
        # serve.postmortems counter), at most one per min-interval so
        # a flapping rule cannot fill the disk with bundles
        self.postmortem_engine = postmortem_engine
        self.postmortem_min_interval_s = float(postmortem_min_interval_s)
        self._last_postmortem_t = None
        self.on_breach = on_breach
        self.on_recover = on_recover

    # -- evaluation --------------------------------------------------------

    def evaluate(self, window, ts=None):
        """Run every rule against one committed window. Called by the
        engines right after their timeseries commit — pure host
        arithmetic on the window record, zero syncs, zero retraces.
        Returns the list of rules that EDGED into breach this window
        (usually empty)."""
        edges = []
        self.windows_evaluated += 1
        self.last_window_idx = window['idx']
        for rule in self.rules:
            st = self._state[rule.name]
            st['windows_evaluated'] += 1
            value = rule.evaluate(window, ts)
            st['last_value'] = value
            if value is None:
                # missing evidence: never counts TOWARD a breach, and
                # never TOWARD a recovery either — both streaks reset,
                # so breach still needs for_windows CONSECUTIVE
                # breaching windows and recovery clear_windows
                # CONSECUTIVE healthy ones, with actual data in each.
                # An engine that stops reporting while breached stays
                # breached.
                st['last'] = 'no_data'
                st['true_streak'] = 0
                st['false_streak'] = 0
                continue
            cond = _OPS[rule.op](value, rule.threshold)
            if cond:
                st['last'] = 'breaching'
                st['true_streak'] += 1
                st['false_streak'] = 0
                if (st['state'] == 'ok'
                        and st['true_streak'] >= rule.for_windows):
                    self._edge_breach(rule, st, window, value)
                    edges.append(rule)
            else:
                st['last'] = 'ok'
                st['false_streak'] += 1
                st['true_streak'] = 0
                if (st['state'] == 'breach'
                        and st['false_streak'] >= rule.clear_windows):
                    self._edge_recover(rule, st, window, value)
        self._inc('watchdog.evaluations')
        breaching = self.breaching()
        self._set_gauge('watchdog.healthy',
                        0.0 if breaching else 1.0)
        self._set_gauge('watchdog.breaching_rules', len(breaching))
        return edges

    # -- scoped telemetry (private registry/journal when configured) -------

    def _inc(self, name, n=1):
        if self.registry is None:
            _metrics.inc(name, n)
        elif _metrics.enabled():
            self.registry.counter(name).inc(n)

    def _set_gauge(self, name, v):
        if self.registry is None:
            _metrics.set_gauge(name, v)
        elif _metrics.enabled():
            self.registry.gauge(name).set(v)

    def _record(self, kind, **fields):
        (self.journal if self.journal is not None
         else _journal.JOURNAL).record(kind, **fields)

    def _edge_breach(self, rule, st, window, value):
        st['state'] = 'breach'
        st['breaches'] += 1
        st['breached_at_idx'] = window['idx']
        self.breaches_total += 1
        self._inc('watchdog.breaches')
        self._record('slo_breach', rule=rule.name, expr=rule.expr,
                     op=rule.op, threshold=rule.threshold,
                     value=_num(value), windows=st['true_streak'],
                     window_idx=window['idx'])
        if self.on_breach is not None:
            self.on_breach(rule, st)
        self._maybe_postmortem(rule, value)

    def _edge_recover(self, rule, st, window, value):
        st['state'] = 'ok'
        st['recoveries'] += 1
        self.recoveries_total += 1
        self._inc('watchdog.recoveries')
        # clamped at 0: after a snapshot/restore failover the carried
        # breached_at_idx indexes the PRIMARY's ring while this ring
        # restarted at 0 — the true duration spans two rings and is
        # unknowable here, so report 0 rather than a negative count
        since = st['breached_at_idx']
        breached = (max(0, window['idx'] - since)
                    if since is not None else None)
        self._record('slo_recovered', rule=rule.name,
                     value=_num(value),
                     breached_windows=breached,
                     window_idx=window['idx'])
        if self.on_recover is not None:
            self.on_recover(rule, st)

    def _maybe_postmortem(self, rule, value):
        eng = self.postmortem_engine
        if eng is None or not getattr(eng, 'postmortem_dir', None):
            return
        now = time.perf_counter()
        if (self._last_postmortem_t is not None
                and now - self._last_postmortem_t
                < self.postmortem_min_interval_s):
            return
        self._last_postmortem_t = now
        try:
            eng._auto_postmortem(RuntimeError(
                f'slo breach: {rule.name} ({rule.expr} {rule.op} '
                f'{rule.threshold}, value {_num(value)})'))
        except Exception:       # noqa: BLE001 - forensics never crash serving
            pass

    # -- verdict / state ---------------------------------------------------

    def breaching(self):
        """Names of the rules currently in breach (sorted)."""
        return sorted(n for n, st in self._state.items()
                      if st['state'] == 'breach')

    def healthy(self):
        return not self.breaching()

    def verdict(self):
        """The machine-readable health answer: healthy iff NO rule is
        in breach. What `/healthz` serializes (plus drain state, which
        is the engine's, not the watchdog's)."""
        breaching = self.breaching()
        return {'healthy': not breaching, 'breaching': breaching,
                'rules': len(self.rules),
                'windows_evaluated': self.windows_evaluated,
                'breaches_total': self.breaches_total,
                'recoveries_total': self.recoveries_total,
                'last_window_idx': self.last_window_idx}

    def state(self):
        """Per-rule config + live state — what `/slo` serves."""
        return {r.name: {**r.config(), **self._state[r.name]}
                for r in self.rules}

    def snapshot_state(self):
        """JSON-able mutable state (per-rule + totals) — rides
        `ServingEngine.snapshot()` so a restored standby continues the
        primary's health history."""
        return {'schema': 1,
                'rules': {n: dict(st) for n, st in self._state.items()},
                'windows_evaluated': self.windows_evaluated,
                'breaches_total': self.breaches_total,
                'recoveries_total': self.recoveries_total,
                # rides too (schema-1 compatible addition) so a
                # restored standby's verdict() reports the primary's
                # last evaluated window instead of a fresh -1
                'last_window_idx': self.last_window_idx}

    def load_state(self, snap):
        """Adopt a `snapshot_state()`. Rules are matched BY NAME:
        state for rules this watchdog does not define is dropped, and
        rules the snapshot never saw keep their fresh state (a standby
        with an extended ruleset restores cleanly). Returns the number
        of rules adopted."""
        if not snap or snap.get('schema') != 1:
            raise ValueError(
                f"unsupported watchdog state schema "
                f"{(snap or {}).get('schema')!r}")
        adopted = 0
        for name, st in (snap.get('rules') or {}).items():
            mine = self._state.get(name)
            if mine is None:
                continue
            for k in mine:
                if k in st:
                    mine[k] = st[k]
            adopted += 1
        self.windows_evaluated = int(snap.get('windows_evaluated', 0))
        self.breaches_total = int(snap.get('breaches_total', 0))
        self.recoveries_total = int(snap.get('recoveries_total', 0))
        lw = snap.get('last_window_idx')   # absent pre-PR-18 snapshots
        self.last_window_idx = int(lw) if lw is not None else None
        return adopted


def _num(v):
    """Journal-safe number: python float/int only (the journal's
    primitives contract)."""
    if isinstance(v, bool) or v is None:
        return v
    try:
        f = float(v)
    except (TypeError, ValueError):
        return repr(v)
    return int(f) if f.is_integer() else round(f, 6)


def default_serving_rules(*, engine=None, ttft_p99_ms=10_000.0,
                          itl_p99_ms=1_000.0, error_rate=0.25,
                          queue_depth=None, pool_pressure=1.0,
                          mfu_floor=0.0, spec_accept_floor=0.0,
                          for_windows=3, clear_windows=2):
    """The production serving ruleset (docs/observability.md catalogs
    each row). Thresholds are keyword-tunable; the defaults are loose
    ceilings meant to catch an engine that is WRONG, not one that is
    merely busy:

      - ttft_p99 / itl_p99: windowed p99 latency ceilings;
      - error_rate: failed fraction of submissions in the window;
      - steady_retraces: ANY compile.traces growth sustained for
        `for_windows` windows — warmup bursts are shorter than the
        hysteresis by construction, steady-state retraces are the
        serving contract's cardinal sin;
      - pool_pressure / queue_depth: saturation watermarks
        (queue_depth defaults to 90% of the engine's max_queue when an
        engine with a bounded queue is passed; unbounded configs get
        no queue rule unless a threshold is given);
      - trace_drops / journal_drops: observability self-health — the
        forensics rings are overflowing, so the NEXT incident would be
        blind (single-window trigger: any sustained growth pages);
      - mfu_floor: `serve.mfu_est` below the floor while costs are
        loaded (no data — costs absent — never breaches). The default
        floor 0.0 makes the rule present-but-inert; give a real floor
        once the deployment's expected MFU is known.
      - spec_accept_floor: `serve.spec_accept_rate` (the windowed
        accepted/proposed draft-token ratio the timeseries ring
        publishes) below the floor — a collapsing accept rate means
        the draft has drifted off the traffic and speculation is now
        COSTING throughput. Inert at the default 0.0 (the rate is
        never negative; non-speculative engines publish no gauge, so
        the rule sees no data and never pages); give a real floor once
        the deployment's steady accept rate is known.
    """
    rules = [
        SLORule('ttft_p99', 'p99(serve.ttft_ms)', '>', ttft_p99_ms,
                for_windows=for_windows, clear_windows=clear_windows,
                help='windowed p99 time-to-first-token ceiling (ms)'),
        SLORule('itl_p99', 'p99(serve.itl_ms)', '>', itl_p99_ms,
                for_windows=for_windows, clear_windows=clear_windows,
                help='windowed p99 inter-token latency ceiling (ms)'),
        SLORule('error_rate', 'ratio(serve.failed,serve.requests)', '>',
                error_rate, for_windows=max(1, for_windows - 1),
                clear_windows=clear_windows,
                help='failed fraction of submissions in the window'),
        SLORule('steady_retraces', 'delta(compile.traces)', '>', 0,
                for_windows=max(3, for_windows),
                clear_windows=clear_windows,
                help='zero steady-state retraces: sustained trace '
                     'growth means the jit keys are flapping'),
        SLORule('pool_pressure', 'gauge(serve.pool_pressure)', '>=',
                pool_pressure, for_windows=for_windows,
                clear_windows=clear_windows,
                help='KV pool at/over the admission watermark'),
        SLORule('trace_drops', 'delta(trace.dropped_events)', '>', 0,
                for_windows=1, clear_windows=clear_windows,
                help='host-tracer ring overflowing (forensics at risk)'),
        SLORule('journal_drops', 'delta(journal.dropped_events)', '>', 0,
                for_windows=1, clear_windows=clear_windows,
                help='flight-recorder ring overflowing'),
        SLORule('mfu_floor', 'gauge(serve.mfu_est)', '<', mfu_floor,
                for_windows=for_windows, clear_windows=clear_windows,
                help='MFU below floor while dispatch costs are loaded'),
        SLORule('spec_accept_floor', 'gauge(serve.spec_accept_rate)',
                '<', spec_accept_floor, for_windows=for_windows,
                clear_windows=clear_windows,
                help='speculative accept rate below floor — the draft '
                     'has drifted off the traffic'),
    ]
    if queue_depth is None and engine is not None:
        mq = getattr(engine, 'max_queue', None)
        if mq:
            queue_depth = 0.9 * mq
    if queue_depth is not None:
        rules.append(SLORule(
            'queue_depth', 'gauge(serve.queue_depth)', '>=',
            queue_depth, for_windows=for_windows,
            clear_windows=clear_windows,
            help='request queue near its bound'))
    return rules
