"""paddle_tpu.observability — unified runtime telemetry.

The system-wide view the private counters (`trace_counts()`,
`BlockAllocator.stats()`, windowed metric sync) never gave: one
process-global metrics registry + one host-span tracer, threaded
through the serving engine, the train engine, the dataloader, and the
compile caches. Rebuilds the reference's Profiler/event-collation
subsystem jax-natively: `jax.profiler` keeps the device timeline, this
package owns the host one, and `tracing.annotate` /
`profiler.RecordEvent` bridge the two.

Contracts (tested in tests/test_observability.py, gated in bench.py):
  - zero device syncs: every record happens at an EXISTING host point
    (the per-window commit, the train sync, the prefetch loop) on data
    the host already has;
  - tracelint-clean: no jit, no donation, no host syncs to police;
  - bounded: fixed-bucket histograms, ring-buffered tracer;
  - cheap: telemetry-on serving stays within 3% of telemetry-off
    (`gate_observability_overhead`).

See docs/observability.md for the metric catalog and span taxonomy.
"""
from __future__ import annotations

from . import metrics, tracing  # noqa: F401
from .metrics import (  # noqa: F401
    REGISTRY, Counter, Gauge, Histogram, MetricsRegistry, enabled,
    inc, observe, set_enabled, set_gauge,
)
from .tracing import (  # noqa: F401
    TRACER, HostTracer, annotate, compile_event, instant, span,
)

__all__ = [
    'metrics', 'tracing',
    'REGISTRY', 'Counter', 'Gauge', 'Histogram', 'MetricsRegistry',
    'enabled', 'set_enabled', 'inc', 'set_gauge', 'observe',
    'TRACER', 'HostTracer', 'span', 'instant', 'compile_event',
    'annotate',
]
