"""paddle_tpu.observability — unified runtime telemetry.

The system-wide view the private counters (`trace_counts()`,
`BlockAllocator.stats()`, windowed metric sync) never gave: one
process-global metrics registry + one host-span tracer, threaded
through the serving engine, the train engine, the dataloader, and the
compile caches. Rebuilds the reference's Profiler/event-collation
subsystem jax-natively: `jax.profiler` keeps the device timeline, this
package owns the host one, and `tracing.annotate` /
`profiler.RecordEvent` bridge the two.

Contracts (tested in tests/test_observability.py, gated in bench.py):
  - zero device syncs: every record happens at an EXISTING host point
    (the per-window commit, the train sync, the prefetch loop) on data
    the host already has;
  - tracelint-clean: no jit, no donation, no host syncs to police;
  - bounded: fixed-bucket histograms, ring-buffered tracer;
  - cheap: telemetry-on serving stays within 3% of telemetry-off
    (`gate_observability_overhead`).

The forensic + cost layer rides on top: `journal` (the flight
recorder — bounded event journal with complete per-request trails),
`costs` (one normalized reading of XLA's compile-time cost model,
feeding the AOT manifest and the live MFU/roofline gauges), and
`postmortem` (crash bundles composing metrics + trace + journal +
engine snapshot).

The LIVE operability layer answers "is this engine healthy right
now": `timeseries` (fixed-interval windowed rings over the registry —
rates, deltas, rolling percentiles — committed at the existing sync
points), `watchdog` (declarative SLO rules with hysteresis and a
machine-readable verdict, breaches journaled), and `httpd` (the
opt-in stdlib ops endpoint: /metrics, /healthz, /statusz, /slo).

See docs/observability.md for the metric catalog and span taxonomy.
"""
from __future__ import annotations

from . import (  # noqa: F401
    costs, httpd, journal, metrics, postmortem, timeseries, tracing,
    watchdog,
)
from .httpd import OpsServer, start_ops_server  # noqa: F401
from .journal import (  # noqa: F401
    JOURNAL, Journal, journal_enabled, set_journal_enabled,
    trail, trail_complete,
)
from .metrics import (  # noqa: F401
    REGISTRY, Counter, Gauge, Histogram, MetricsRegistry, enabled,
    inc, observe, set_enabled, set_gauge,
)
from .postmortem import dump_bundle, load_bundle, validate_bundle  # noqa: F401
from .timeseries import TIMESERIES, WindowedTimeseries  # noqa: F401
from .tracing import (  # noqa: F401
    TRACER, HostTracer, annotate, compile_event, instant, span,
)
from .watchdog import SLORule, Watchdog, default_serving_rules  # noqa: F401

__all__ = [
    'metrics', 'tracing', 'journal', 'costs', 'postmortem',
    'timeseries', 'watchdog', 'httpd',
    'REGISTRY', 'Counter', 'Gauge', 'Histogram', 'MetricsRegistry',
    'enabled', 'set_enabled', 'inc', 'set_gauge', 'observe',
    'TRACER', 'HostTracer', 'span', 'instant', 'compile_event',
    'annotate',
    'JOURNAL', 'Journal', 'journal_enabled', 'set_journal_enabled',
    'trail', 'trail_complete',
    'dump_bundle', 'validate_bundle', 'load_bundle',
    'TIMESERIES', 'WindowedTimeseries',
    'SLORule', 'Watchdog', 'default_serving_rules',
    'OpsServer', 'start_ops_server',
]
