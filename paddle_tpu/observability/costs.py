"""costs — ONE normalized reading of XLA's compile-time cost model.

Before this module, `compiled.cost_analysis()` was queried in three
independent places (`utils.flops`, `profiler.op_summary`,
`jit.compilation_report`), each re-discovering the same quirks: some
jax versions return a LIST of per-partition dicts instead of a dict,
the call can raise outright on exotic backends, keys are
space-separated strings ('bytes accessed'), and `memory_analysis` has
its own failure modes. `analyze()` handles all of it once and returns
one stable shape; the old call sites now delegate here, and
`aot.build` uses it to stamp per-geometry flops+bytes into the
artifact manifest — the static numbers the serving and train engines
turn into live `serve.mfu_est` / `train.mfu_est` / roofline gauges at
their existing window-commit syncs (host arithmetic on host-known wall
times: zero new device syncs, zero retraces on the hot path).

Everything here is compile-time/host-side; the only jax touches are
lazy (inside the helpers that take jitted functions or query devices),
so the module imports cleanly without a backend.
"""
from __future__ import annotations

import os

__all__ = ['analyze', 'analyze_jitted', 'intensity', 'geometry_cost',
           'measure_dispatch_costs', 'device_peak_flops',
           'PEAK_BF16_FLOPS']

# normalized field -> cost_analysis key
_COST_FIELDS = (('flops', 'flops'),
                ('bytes_accessed', 'bytes accessed'),
                ('transcendentals', 'transcendentals'))

# per-chip dense bf16 peak (the bench.py table; longest-prefix matched
# so 'TPU v5 lite' cannot shadow 'TPU v5p' or vice versa)
PEAK_BF16_FLOPS = {
    'TPU v2': 45e12, 'TPU v3': 123e12, 'TPU v4': 275e12,
    'TPU v5 lite': 197e12, 'TPU v5e': 197e12, 'TPU v5': 459e12,
    'TPU v5p': 459e12, 'TPU v6 lite': 918e12, 'TPU v6e': 918e12,
}


def analyze(compiled):
    """Normalized cost view of one compiled executable:

        {'flops': float|None, 'bytes_accessed': float|None,
         'transcendentals': float|None,
         'memory': {'argument_bytes', 'output_bytes', 'temp_bytes'}}

    Accepts a `Compiled` OR a `Lowered` (compiled here; a compile
    failure degrades to all-None instead of raising). Handles the
    list-vs-dict return quirk, the bare-raise quirk, and missing keys
    — the one place those are allowed to exist."""
    out = {'flops': None, 'bytes_accessed': None, 'transcendentals': None,
           'memory': {}}
    if compiled is None:
        return out
    if hasattr(compiled, 'compile'):          # a Lowered: compile first
        try:
            compiled = compiled.compile()
        except Exception:  # noqa: BLE001 - degrade, never raise
            return out
    try:
        cost = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 - exotic backends raise here
        cost = None
    if isinstance(cost, (list, tuple)):       # per-partition list quirk
        cost = cost[0] if cost else None
    if isinstance(cost, dict):
        for field, key in _COST_FIELDS:
            v = cost.get(key)
            if v is not None:
                try:
                    out[field] = float(v)
                except (TypeError, ValueError):
                    pass
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            out['memory'] = {
                'argument_bytes': int(mem.argument_size_in_bytes),
                'output_bytes': int(mem.output_size_in_bytes),
                'temp_bytes': int(mem.temp_size_in_bytes),
            }
    except Exception:  # noqa: BLE001 - memory analysis is best-effort
        pass
    return out


def analyze_jitted(fn, *args, **kwargs):
    """`analyze` of a jitted callable lowered over `args` (args may be
    ShapeDtypeStructs — nothing executes). Lowering re-traces, so keep
    this OFF serving hot paths (it bumps the engines' trace counters)."""
    return analyze(fn.lower(*args, **kwargs))


def intensity(cost):
    """Roofline operational intensity (flops / bytes accessed) of one
    `analyze()` result, or None when either half is unknown/zero."""
    f, b = cost.get('flops'), cost.get('bytes_accessed')
    if not f or not b:
        return None
    return f / b


def geometry_cost(engine, g, draft=None):
    """Static cost of ONE enumerated aot geometry: lower each of its
    dispatch specs (`engine._cost_specs` — the same MODULE-LEVEL jitted
    steps the live scheduler dispatches, with the live model riding as
    an argument, so the analyzed HLO is the served HLO) and sum
    `analyze()` over them. Under `aot.build` the persistent cache is
    already wired, so the `.compile()` inside is a disk read of the
    executable the build just persisted. Raises NotImplementedError for
    kinds without cost specs (speculative windows)."""
    total = {'flops': 0.0, 'bytes_accessed': 0.0, 'transcendentals': 0.0}
    seen = {k: False for k in total}
    n = 0
    for fn, args, kwargs in engine._cost_specs(g, draft=draft):
        c = analyze_jitted(fn, *args, **kwargs)
        n += 1
        for k in total:
            if c[k] is not None:
                total[k] += c[k]
                seen[k] = True
    out = {k: (total[k] if seen[k] else None) for k in total}
    out['specs'] = n
    return out


def measure_dispatch_costs(engine, geometries=None, draft=None):
    """Compute per-geometry costs for a LIVE engine and load them into
    its dispatch-cost table (`_note_geometry_cost`) — the no-artifact
    path `tools/telemetry_dump.py` uses; engines warmed from an
    `aot.EngineArtifact` get the same table from the manifest for free.
    Lowering re-traces, so call this off the serving hot path. Returns
    {geometry label: cost-or-error-string}."""
    from ..aot import geometry as _geometry

    if geometries is None:
        geometries = _geometry.for_engine(engine)
    report = {}
    for g in geometries:
        try:
            c = geometry_cost(engine, g, draft=draft)
        except NotImplementedError as e:
            report[g.label()] = f'skipped: {e}'
            continue
        except Exception as e:  # noqa: BLE001 - per-geometry, not fatal
            report[g.label()] = f'error: {type(e).__name__}: {e}'
            continue
        engine._note_geometry_cost(g, c)
        report[g.label()] = c
    return report


def device_peak_flops(device=None):
    """Peak dense flops/s the MFU denominator divides by:
    `PADDLE_TPU_PEAK_FLOPS` (explicit, any backend — what the bench
    gate pins) wins; else the bf16 table for known TPU kinds; else None
    — an honest "unknown" beats a fabricated MFU, so the engines skip
    the `*.mfu_est` gauge and still record achieved flops/s."""
    env = os.environ.get('PADDLE_TPU_PEAK_FLOPS')
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax

        d = device if device is not None else jax.devices()[0]
    except Exception:  # noqa: BLE001 - no backend: no peak
        return None
    kind = str(getattr(d, 'device_kind', '')).lower()
    best = None
    for k, v in PEAK_BF16_FLOPS.items():
        if kind.startswith(k.lower()):
            if best is None or len(k) > best[0]:
                best = (len(k), v)
    return best[1] if best else None
