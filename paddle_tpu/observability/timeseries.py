"""Windowed timeseries — live rates and rolling percentiles over the
metrics registry.

PR 6's registry answers "what happened since boot": cumulative
counters, since-boot histograms. A router deciding where to send the
next request — or a watchdog deciding whether this replica is healthy
RIGHT NOW — needs the other question: what happened in the last
second. This module derives that view from the cumulative registry
with zero new instrumentation and zero new host syncs:

  - the engines call `maybe_commit()` at their EXISTING host points
    (the serving per-window commit, the train `sync()`), passing the
    perf_counter stamp they already hold. Off the commit boundary it
    is two compares; on it, one pass over the registry;
  - each committed window snapshots every registered metric and diffs
    it against the previous snapshot: counters become `{delta, rate}`,
    histograms become per-window counts + interpolated p50/p95/p99
    over the window's bucket DELTAS (the rolling percentile the
    cumulative histogram can never give back once it has absorbed a
    bad hour), gauges ride as last-written values;
  - well-known counters additionally publish live rate GAUGES back
    into the registry (`serve.tok_s`, `serve.req_s`,
    `serve.preempt_s`, `serve.err_rate`, `train.tok_s`), so the
    Prometheus exposition and `/metrics` carry the windowed rates a
    fleet router reads (ROADMAP item 1's load-aware routing);
  - memory is bounded: a fixed ring of `max_windows` window records
    plus ONE previous-cumulative snapshot, regardless of uptime.

Windows are wall-interval paced (`interval_s`), not step paced: a
commit point landing past the interval closes one window spanning the
ACTUAL elapsed time (`dur_s`), and rates divide by that — an idle
engine produces long truthful windows instead of a backlog of empty
ones. Tests drive `commit(now=...)` directly for exact arithmetic.

Like the rest of the package this module is stdlib-only at import
(no jax, no numpy) and gated by the global telemetry switch
(`metrics.enabled()`). The SLO watchdog (`watchdog.py`) evaluates its
rules against each committed window; the ops endpoint (`httpd.py`)
serves the ring as JSON.
"""
from __future__ import annotations

import collections
import json
import threading
import time

from . import journal as _journal
from . import metrics as _metrics

__all__ = [
    'WindowedTimeseries', 'TIMESERIES', 'DERIVED_RATES',
    'percentile_from_buckets', 'maybe_commit',
]

# counter -> gauge published on every commit: the window's per-second
# rate of the counter's delta. The serving/fleet set the ROADMAP's
# load-aware router polls; absent counters publish nothing.
DERIVED_RATES = (
    ('serve.tokens', 'serve.tok_s'),
    ('serve.requests', 'serve.req_s'),
    ('serve.preemptions', 'serve.preempt_s'),
    ('train.tokens', 'train.tok_s'),
)

# the serving terminal-state counters: `serve.err_rate` is the
# window's failed fraction of terminal outcomes (None published — the
# gauge left untouched — on a window with no terminals)
_TERMINAL_COUNTERS = ('serve.finished', 'serve.failed', 'serve.expired',
                      'serve.cancelled')


def percentile_from_buckets(edges, counts, p):
    """Interpolated p-th percentile over ONE window's bucket counts
    (the registry Histogram's algorithm applied to deltas). The first
    bucket interpolates from 0, and the +inf bucket clamps to the last
    finite edge — a window has no observed min/max, only its bucket
    deltas, so the estimate is exact to bucket resolution. None when
    the window saw no observations."""
    total = sum(counts)
    if total == 0:
        return None
    rank = (p / 100.0) * total
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        prev_cum = cum
        cum += c
        if cum >= rank:
            if i == len(edges):          # +inf bucket
                return edges[-1]
            lo = edges[i - 1] if i > 0 else 0.0
            hi = edges[i]
            frac = (rank - prev_cum) / c
            return lo + (hi - lo) * max(0.0, min(1.0, frac))
    return edges[-1]


class WindowedTimeseries:
    """Fixed-interval windowed ring over a MetricsRegistry.

    One instance per consumer scope: the module-global `TIMESERIES`
    is the process default (fed by every engine that has no private
    operability config), while a `ServingEngine(watchdog=...)` or
    `(ops_port=...)` owns a private instance so its SLO windows are
    isolated from other engines in the process. Thread-safe for the
    ops-endpoint reader: `commit` and the read accessors share one
    lock (uncontended — commits happen once per interval)."""

    def __init__(self, interval_s=1.0, max_windows=120, registry=None,
                 derive=True, journal=None):
        self.interval_s = float(interval_s)
        if self.interval_s <= 0:
            raise ValueError('interval_s must be > 0')
        self.max_windows = int(max_windows)
        if self.max_windows < 1:
            raise ValueError('max_windows must be >= 1')
        self.registry = registry if registry is not None else _metrics.REGISTRY
        # which journal's overflow count rides the windows as the
        # `journal.dropped_events` pseudo-counter — a private-registry
        # replica passes its private journal so its drop-rate windows
        # never read another replica's ring
        self.journal = journal if journal is not None else _journal.JOURNAL
        self.derive = bool(derive)
        self._ring: collections.deque = collections.deque(
            maxlen=self.max_windows)
        self._lock = threading.Lock()
        self._idx = 0                 # total windows ever committed
        self._prev = None             # cumulative baseline snapshot
        self._prev_t = None
        self._prev_gen = None
        self._edges: dict = {}        # histogram name -> bucket edges

    # -- committing --------------------------------------------------------

    def _cumulative(self):
        """One pass over the registry: {'counters': {name: value},
        'gauges': {name: value}, 'hists': {name: (counts, count, sum)}}
        plus the journal's overflow count as a pseudo-counter (the
        watchdog's journal-overflow-growth rule reads its delta)."""
        counters, gauges, hists = {}, {}, {}
        for name in self.registry.names():
            m = self.registry.get(name)
            if m is None:
                continue
            if m.kind == 'counter':
                counters[name] = m.value
            elif m.kind == 'gauge':
                gauges[name] = m.value
            else:
                self._edges[name] = m.edges
                hists[name] = (tuple(m.counts), m.count, m.sum)
        counters['journal.dropped_events'] = self.journal.dropped
        return {'counters': counters, 'gauges': gauges, 'hists': hists}

    def _rebase(self, now):
        self._prev = self._cumulative()
        self._prev_t = now
        self._prev_gen = self.registry.generation

    def maybe_commit(self, now=None):
        """Commit one window iff the interval has elapsed since the
        last commit (or baseline). The engines call this at their
        existing sync points with the perf_counter stamp already in
        hand; the miss path is two compares (the unlocked interval
        read is a benign race — the interval is re-checked under the
        lock, so two threads sharing one ring can never double-commit
        a degenerate zero-duration window). Returns the committed
        window dict, or None."""
        if not _metrics.enabled():
            return None
        if now is None:
            now = time.perf_counter()
        if (self._prev_t is not None
                and now - self._prev_t < self.interval_s):
            return None
        return self._commit(now, require_interval=True)

    def commit(self, now=None):
        """Force-close the current window at `now` regardless of the
        interval (tests and the dump tool use this for exact, clock-
        independent arithmetic). A registry `reset()` since the last
        baseline re-reads the baseline as zero — counters restarted,
        so the delta IS the current cumulative value, never negative.
        Returns the committed window dict, or None with telemetry
        off."""
        if not _metrics.enabled():
            return None
        if now is None:
            now = time.perf_counter()
        return self._commit(now, require_interval=False)

    def _commit(self, now, require_interval):
        with self._lock:
            if self._prev_t is None:   # first call opens the window
                self._rebase(now)
                return None
            if (require_interval
                    and now - self._prev_t < self.interval_s):
                return None            # another thread just committed
            cur = self._cumulative()
            prev = self._prev
            if self._prev_gen != self.registry.generation:
                prev = {'counters': {}, 'gauges': {}, 'hists': {}}
            dt = max(now - self._prev_t, 1e-9)
            window = {'idx': self._idx, 't0': self._prev_t, 't1': now,
                      'dur_s': dt, 'counters': {}, 'gauges': {},
                      'hists': {}}
            for name, v in cur['counters'].items():
                # clamped at 0: registry counters only shrink across a
                # reset (caught by the generation check above), but the
                # journal-overflow pseudo-counter can also shrink on a
                # JOURNAL.clear() — a negative "events dropped" rate is
                # never the truthful answer
                d = max(v - prev['counters'].get(name, 0), 0)
                window['counters'][name] = {'delta': d, 'rate': d / dt}
            window['gauges'] = dict(cur['gauges'])
            for name, (counts, count, total) in cur['hists'].items():
                pc, pn, ps = prev['hists'].get(
                    name, ((0,) * len(counts), 0, 0.0))
                if len(pc) != len(counts):    # re-registered, new buckets
                    pc = (0,) * len(counts)
                dcounts = [c - p for c, p in zip(counts, pc)]
                dcount = count - pn
                dsum = total - ps
                edges = self._edges[name]
                window['hists'][name] = {
                    'count': dcount, 'sum': dsum,
                    'rate': dcount / dt,
                    'mean': (dsum / dcount) if dcount > 0 else None,
                    'p50': percentile_from_buckets(edges, dcounts, 50),
                    'p95': percentile_from_buckets(edges, dcounts, 95),
                    'p99': percentile_from_buckets(edges, dcounts, 99),
                    'buckets': dcounts,
                }
            self._ring.append(window)
            self._idx += 1
            self._prev = cur
            self._prev_t = now
            self._prev_gen = self.registry.generation
            # published INSIDE the ring lock: two threads sharing the
            # process-default ring must publish in window order, or a
            # descheduled earlier committer could overwrite a newer
            # window's serve.tok_s with stale rates. Lock order is
            # ring -> registry only (the registry never takes a ring
            # lock), so no inversion is possible.
            if self.derive:
                self._publish_derived(window)
        return window

    def _publish_derived(self, window):
        """Windowed rates back into THIS ring's registry as gauges —
        the live `serve.tok_s` a fleet router polls off `/metrics`.
        Published into `self.registry` (not the process global), so
        the private-registry isolation recipe carries its own rate
        gauges instead of clobbering another replica's."""
        if not _metrics.enabled():
            return
        ctrs = window['counters']
        for counter, gauge in DERIVED_RATES:
            c = ctrs.get(counter)
            if c is not None:
                self.registry.gauge(gauge).set(c['rate'])
        terms = [ctrs[n]['delta'] for n in _TERMINAL_COUNTERS if n in ctrs]
        total = sum(terms)
        if total > 0:
            failed = ctrs.get('serve.failed', {}).get('delta', 0)
            self.registry.gauge('serve.err_rate').set(failed / total)
        # windowed speculative accept rate: accepted draft tokens over
        # proposed, THIS window only (untouched on windows with no
        # proposals — a drained engine keeps its last reading instead
        # of snapping to a meaningless 0)
        sp = ctrs.get('serve.spec_proposed')
        if sp is not None and sp['delta'] > 0:
            acc = ctrs.get('serve.spec_accepted', {}).get('delta', 0)
            self.registry.gauge('serve.spec_accept_rate').set(
                acc / sp['delta'])

    # -- reading -----------------------------------------------------------

    def __len__(self):
        return len(self._ring)

    def last(self):
        """The most recently committed window, or None."""
        with self._lock:
            return self._ring[-1] if self._ring else None

    def windows(self, n=None):
        """The last `n` committed windows, oldest first (all of the
        ring when n is None)."""
        with self._lock:
            ws = list(self._ring)
        return ws if n is None else ws[-int(n):]

    def rate(self, name, windows=1):
        """Average per-second rate of counter `name` over the last
        `windows` committed windows (delta sums over duration sums);
        None when nothing is committed or the counter never appeared."""
        ws = self.windows(windows)
        ds = [w['counters'][name]['delta'] for w in ws
              if name in w['counters']]
        if not ds:
            return None
        dur = sum(w['dur_s'] for w in ws if name in w['counters'])
        return sum(ds) / dur if dur > 0 else None

    def delta(self, name, windows=1):
        """Summed counter (or histogram-count) delta over the last
        `windows` windows; None when the metric never appeared."""
        ws = self.windows(windows)
        out = None
        for w in ws:
            if name in w['counters']:
                out = (out or 0) + w['counters'][name]['delta']
            elif name in w['hists']:
                out = (out or 0) + w['hists'][name]['count']
        return out

    def gauge(self, name):
        """Gauge value as of the last committed window, or None."""
        w = self.last()
        return w['gauges'].get(name) if w else None

    def wpercentile(self, name, p, windows=1):
        """Rolling percentile of histogram `name` over the last
        `windows` windows' MERGED bucket deltas — the SLO view
        ('p99 TTFT over the last minute'), immune to everything the
        cumulative histogram absorbed before that."""
        ws = self.windows(windows)
        merged = None
        for w in ws:
            h = w['hists'].get(name)
            if h is None:
                continue
            if merged is None:
                merged = list(h['buckets'])
            else:
                merged = [a + b for a, b in zip(merged, h['buckets'])]
        if merged is None:
            return None
        return percentile_from_buckets(self._edges[name], merged, p)

    def snapshot(self):
        """JSON-able view of the ring — the timeseries.json artifact."""
        return {'interval_s': self.interval_s,
                'max_windows': self.max_windows,
                'committed': self._idx,
                'windows': self.windows()}

    def to_json(self, **kw):
        return json.dumps(self.snapshot(), **kw)

    def reset(self):
        """Drop the ring and the baseline (test isolation)."""
        with self._lock:
            self._ring.clear()
            self._idx = 0
            self._prev = None
            self._prev_t = None
            self._prev_gen = None


# process default: fed by every engine without a private operability
# config (ServingEngine steps and TrainEngine sync() both call
# maybe_commit on it), read by tools/telemetry_dump.py and the
# standalone ops server
TIMESERIES = WindowedTimeseries()


def maybe_commit(now=None):
    """Module-level convenience over the process-default ring."""
    return TIMESERIES.maybe_commit(now)
