"""postmortem — crash forensics bundles.

When a serving worker dies (PR 8's `dispatch kind='window'` fault
model, or any exception escaping `ServingEngine.step()`), the operator
used to get a traceback and a counter bump. `dump_bundle(dir)`
composes everything the process knows into one directory a human (or
`tools/postmortem.py`) can read after the fact:

    bundle.json       manifest: schema, env fingerprint, the error,
                      engine census (stats() — allocator, geometry,
                      resilience counters — plus the geometry-cost
                      table), per-file status
    metrics.json      full MetricsRegistry snapshot
    host_trace.json   HostTracer Chrome trace_event array
    journal.jsonl     flight-recorder tail (newest events)
    snapshot.json     engine.snapshot() — the restorable host state,
                      when the engine has one

Every artifact is best-effort: a failure writing one piece is recorded
in bundle.json's `errors` and never raised — forensics must not mask
the crash being recorded. `ServingEngine(postmortem_dir=...)` (or env
`PADDLE_TPU_POSTMORTEM_DIR`) auto-dumps a bundle on the worker-death
path before re-raising; `validate_bundle` is the CLI's and the bench
gate's acceptance check.

Stdlib-only at import (the env fingerprint reaches for jax lazily), so
bundles can be read and validated on boxes with no backend at all.
"""
from __future__ import annotations

import json
import os
import platform
import sys
import time
import traceback

from . import journal as _journal
from . import metrics as _metrics
from . import tracing as _tracing

__all__ = ['BUNDLE_SCHEMA', 'BUNDLE_NAME', 'dump_bundle',
           'validate_bundle', 'load_bundle', 'env_fingerprint']

BUNDLE_SCHEMA = 1
BUNDLE_NAME = 'bundle.json'

# journal slice size in a bundle: enough for the whole incident window,
# bounded so a bundle is always a quick read/copy
JOURNAL_TAIL = 20_000

# env var prefixes worth fingerprinting (config, never secrets)
_ENV_PREFIXES = ('PADDLE_TPU_', 'JAX_', 'XLA_FLAGS', 'LIBTPU')


def env_fingerprint():
    """The process environment a postmortem reader needs to reproduce:
    versions, backend, and the PADDLE_TPU_/JAX_/XLA knobs that were
    set. jax is optional — a backendless box still fingerprints."""
    fp = {
        'python': sys.version.split()[0],
        'platform': platform.platform(),
        'pid': os.getpid(),
        'argv': list(sys.argv),
        'env': {k: v for k, v in sorted(os.environ.items())
                if k.startswith(_ENV_PREFIXES)},
    }
    try:
        import jax
        import jaxlib

        fp['jax'] = jax.__version__
        fp['jaxlib'] = jaxlib.__version__
        fp['backend'] = jax.default_backend()
        fp['device_kind'] = getattr(jax.devices()[0], 'device_kind', '?')
    except Exception as e:  # noqa: BLE001 - no backend is a valid state
        fp['jax_error'] = f'{type(e).__name__}: {e}'
    return fp


def _error_record(error):
    if error is None:
        return None
    rec = {'type': type(error).__name__, 'repr': repr(error)}
    tb = getattr(error, '__traceback__', None)
    if tb is not None:
        rec['traceback'] = ''.join(
            traceback.format_exception(type(error), error, tb))[-8000:]
    return rec


def dump_bundle(out_dir, engine=None, error=None, reason=None,
                extra=None):
    """Write one postmortem bundle into `out_dir` (created). Returns a
    report dict: {'path', 'written': [...], 'errors': {file: why}}.
    NEVER raises past argument validation — each artifact is written
    independently and failures are recorded in the manifest."""
    out_dir = os.path.abspath(out_dir)
    os.makedirs(out_dir, exist_ok=True)
    written, errors = [], {}

    def _write(name, producer):
        try:
            producer(os.path.join(out_dir, name))
            written.append(name)
        except Exception as e:  # noqa: BLE001 - forensics: record, go on
            errors[name] = f'{type(e).__name__}: {e}'

    def _dump_json(path, payload):
        with open(path, 'w') as f:
            json.dump(payload, f, indent=2, default=str)

    def _json_to(name, payload):
        _write(name, lambda p: _dump_json(p, payload))

    # a private-registry replica's bundle carries ITS series and ITS
    # flight recorder (the fleet's kill-resurrection reads them back);
    # default engines keep dumping the process globals byte-for-byte
    reg = getattr(engine, '_registry', None)
    reg = reg if reg is not None else _metrics.REGISTRY
    jr = getattr(engine, '_jr', None)
    jr = jr if jr is not None else _journal.JOURNAL

    _json_to('metrics.json', reg.snapshot())
    _write('host_trace.json', _tracing.TRACER.export)
    _write('journal.jsonl',
           lambda p: jr.save(p, tail=JOURNAL_TAIL))

    census = None
    if engine is not None:
        try:
            census = engine.stats()
        except Exception as e:  # noqa: BLE001
            errors['stats'] = f'{type(e).__name__}: {e}'
        costs = getattr(engine, '_dispatch_costs', None)
        if costs:
            # the geometry-cost census: what the MFU gauges divide by
            census = dict(census or {})
            census['dispatch_costs'] = {str(k): v
                                        for k, v in costs.items()}
        if hasattr(engine, 'snapshot'):
            snap = None
            try:
                snap = engine.snapshot()
            except Exception as e:  # noqa: BLE001 - snapshot can refuse
                errors['snapshot.json'] = f'{type(e).__name__}: {e}'
            if snap is not None:
                _json_to('snapshot.json', snap)

    manifest = {
        'schema': BUNDLE_SCHEMA,
        'created_at': time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime()),
        'reason': reason,
        'error': _error_record(error),
        'fingerprint': env_fingerprint(),
        'engine': census,
        'journal': {
            'events': len(jr),
            'dropped': jr.dropped,
            'trails': len(jr.trails()),
        },
        'extra': extra,
        'files': sorted(written),
        'errors': errors,
    }
    _json_to(BUNDLE_NAME, manifest)
    return {'path': out_dir, 'written': sorted(written) + [BUNDLE_NAME],
            'errors': errors}


# files a valid bundle must carry and parse; snapshot.json is optional
# (only engines with snapshot() write it)
_REQUIRED = ('bundle.json', 'metrics.json', 'host_trace.json',
             'journal.jsonl')


def validate_bundle(path):
    """(ok, problems) for a bundle directory: required files exist and
    parse, manifest schema is known, the host trace is a trace_event
    array, every journal line is JSON. The CLI's and the bench gate's
    acceptance check."""
    problems = []
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        return False, [f'not a directory: {path}']
    for name in _REQUIRED:
        if not os.path.isfile(os.path.join(path, name)):
            problems.append(f'missing {name}')
    if problems:
        return False, problems
    try:
        with open(os.path.join(path, BUNDLE_NAME)) as f:
            manifest = json.load(f)
        if manifest.get('schema') != BUNDLE_SCHEMA:
            problems.append(
                f"unknown bundle schema {manifest.get('schema')!r} "
                f'(this reader knows {BUNDLE_SCHEMA})')
        if not isinstance(manifest.get('fingerprint'), dict):
            problems.append('bundle.json lacks the env fingerprint')
    except (OSError, ValueError) as e:
        problems.append(f'bundle.json unreadable: {e}')
    try:
        with open(os.path.join(path, 'metrics.json')) as f:
            if not isinstance(json.load(f), dict):
                problems.append('metrics.json is not an object')
    except (OSError, ValueError) as e:
        problems.append(f'metrics.json unreadable: {e}')
    try:
        with open(os.path.join(path, 'host_trace.json')) as f:
            trace = json.load(f)
        if not isinstance(trace, list) or any(
                not isinstance(e, dict) or 'ph' not in e or 'ts' not in e
                for e in trace):
            problems.append('host_trace.json is not a trace_event array')
    except (OSError, ValueError) as e:
        problems.append(f'host_trace.json unreadable: {e}')
    try:
        with open(os.path.join(path, 'journal.jsonl')) as f:
            for i, line in enumerate(f):
                if line.strip():
                    json.loads(line)
    except (OSError, ValueError) as e:
        problems.append(f'journal.jsonl unreadable: {e}')
    sp = os.path.join(path, 'snapshot.json')
    if os.path.isfile(sp):
        try:
            with open(sp) as f:
                json.load(f)
        except (OSError, ValueError) as e:
            problems.append(f'snapshot.json unreadable: {e}')
    return not problems, problems


def load_bundle(path):
    """Parsed bundle contents: {'manifest', 'metrics', 'host_trace',
    'journal' (list of events), 'snapshot' (or None)}. Raises on a
    bundle `validate_bundle` would reject — validate first when the
    input is untrusted."""
    path = os.path.abspath(path)
    with open(os.path.join(path, BUNDLE_NAME)) as f:
        manifest = json.load(f)
    with open(os.path.join(path, 'metrics.json')) as f:
        metrics = json.load(f)
    with open(os.path.join(path, 'host_trace.json')) as f:
        host_trace = json.load(f)
    journal = []
    with open(os.path.join(path, 'journal.jsonl')) as f:
        for line in f:
            if line.strip():
                journal.append(json.loads(line))
    snapshot = None
    sp = os.path.join(path, 'snapshot.json')
    if os.path.isfile(sp):
        with open(sp) as f:
            snapshot = json.load(f)
    return {'manifest': manifest, 'metrics': metrics,
            'host_trace': host_trace, 'journal': journal,
            'snapshot': snapshot}
