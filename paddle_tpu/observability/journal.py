"""Journal — the flight recorder: a bounded, deterministic structured
event journal for per-request forensics.

PR 6's metrics answer "how is the fleet doing"; this module answers
"what exactly happened to request 1742". Every scheduler decision
(admit / preempt / shed / expire), allocator op (alloc / free / share /
cow / prefix-evict), injected fault, and compile event is one appended
dict, recorded at the SAME existing host points the metrics ride (the
PR-6 zero-sync contract: no device_get, no retrace, a few dict ops per
event). Two views over one stream:

  - the chronological journal: a bounded ring (`max_events`, oldest
    dropped and counted) exported as JSONL — what a postmortem bundle
    tails;
  - per-request trails: `trail(rid)` returns every event of one
    request in order, COMPLETE even when the ring has wrapped — trails
    are kept whole until the request is terminal and the
    `max_trails` bound evicts the oldest CLOSED trail (a live request's
    trail is never evicted, so forensics on an in-flight incident
    cannot lose its head).

Determinism contract: for a fixed workload (same submissions, same
seeded fault script, no wall-clock-dependent config) the SEQUENCE of
events — kind, rid, fields — is identical run to run; only the
timing fields (`TIME_FIELDS`) vary. tests/test_flight_recorder.py and
bench.py's `gate_flight_recorder` pin it.

Trails survive `ServingEngine.snapshot()`/`restore()`: the snapshot
carries each live and unretrieved request's trail, and `restore()`
re-injects them (`inject_trail`) with the seq counter bumped past the
snapshot's, so a post-failover trail is still one ordered record from
arrival to terminal state.

Like the metrics registry, this module is stdlib-only and gated by the
global telemetry switch (`metrics.enabled()`); `set_journal_enabled`
additionally switches JUST the journal (what the flight-recorder
overhead gate diffs).
"""
from __future__ import annotations

import collections
import json

from . import metrics as _metrics

__all__ = ['Journal', 'JOURNAL', 'TERMINAL_KINDS', 'TIME_FIELDS',
           'record', 'trail', 'save', 'tail', 'trail_complete',
           'strip_times', 'set_journal_enabled', 'journal_enabled']

# a trail is CLOSED (evictable once the bound is hit) when one of these
# kinds lands — the serving engine's terminal request states
TERMINAL_KINDS = frozenset(('finished', 'failed', 'expired', 'cancelled'))

# wall-clock fields: excluded from determinism comparisons
# (`strip_times`) — everything else in an event must be reproducible
TIME_FIELDS = frozenset(('t', 'dur_ms'))

_ENABLED = True


def journal_enabled():
    """Whether journal recording is on (both the journal's own switch
    AND the global telemetry switch must be)."""
    return _ENABLED and _metrics.enabled()


def set_journal_enabled(on):
    """Flip ONLY the journal (the global `metrics.set_enabled` still
    gates it too) — the knob `gate_flight_recorder` diffs overhead
    against."""
    global _ENABLED
    _ENABLED = bool(on)


class Journal:
    """Bounded event ring + complete per-request trails."""

    def __init__(self, max_events=100_000, max_trails=4096):
        self.max_events = int(max_events)
        self.max_trails = int(max_trails)
        self._events: collections.deque = collections.deque(
            maxlen=self.max_events)
        self._trails: dict = {}       # rid -> [event, ...] (complete)
        self._closed: dict = {}       # rid -> None, oldest-closed first
        self._seq = 0
        self.dropped = 0              # ring overflow (chronological view
                                      # only; trails never lose events)
        self.trail_evictions = 0

    # -- recording ---------------------------------------------------------

    def record(self, kind, rid=None, t=None, **fields):
        """Append one event. `fields` must be JSON primitives (or short
        lists of them) — the caller's contract; `t` is a perf_counter
        stamp when the caller already holds one (a TIME_FIELD, excluded
        from determinism comparisons)."""
        # hot path: serving records a handful of events per scheduler
        # step, so the off-switch is two module attribute reads (no
        # function call) and the on-path is one dict + two appends
        if not _ENABLED or not _metrics._ENABLED:
            return None
        ev = {'seq': self._seq, 'kind': kind}
        self._seq += 1
        if rid is not None:
            ev['rid'] = rid
        if t is not None:
            ev['t'] = t
        if fields:
            ev.update(fields)
        if len(self._events) == self.max_events:
            self.dropped += 1
        self._events.append(ev)
        if rid is not None:
            tr = self._trails.get(rid)
            if tr is None:
                tr = self._trails[rid] = []
                self._evict()        # a NEW trail may push past the bound
            tr.append(ev)
            if kind in TERMINAL_KINDS:
                self._close(rid)
        return ev

    def _append(self, ev):
        if len(self._events) == self.max_events:
            self.dropped += 1
        self._events.append(ev)

    def _close(self, rid):
        self._closed[rid] = None
        self._evict()

    def _evict(self):
        """Drop oldest-CLOSED trails past `max_trails`. Live trails are
        never evicted (forensics on an in-flight incident must keep its
        head) — an all-live overshoot is bounded by the engine's own
        queue/slot/terminal bounds."""
        while len(self._trails) > self.max_trails and self._closed:
            victim = next(iter(self._closed))
            del self._closed[victim]
            self._trails.pop(victim, None)
            self.trail_evictions += 1

    def inject_trail(self, rid, events):
        """Re-register a trail from a snapshot (the restore path).
        Injected events keep their original seq/ts; the journal's own
        counter jumps past the highest injected seq so later events
        stay ordered after them. Events whose seq the existing trail
        already covers are skipped — a same-process restore (hot
        standby sharing this journal) injects nothing and duplicates
        nothing. Returns the number of events injected."""
        if not (_ENABLED and _metrics.enabled()):
            return 0
        cur = self._trails.get(rid)
        last = max((e.get('seq', -1) for e in cur), default=-1) \
            if cur else -1
        evs = [dict(e) for e in events if e.get('seq', -1) > last]
        if not evs:
            return 0
        for ev in evs:
            self._append(ev)
        self._trails.setdefault(rid, []).extend(evs)
        mx = max(e.get('seq', -1) for e in evs)
        if mx >= self._seq:
            self._seq = mx + 1
        if any(e.get('kind') in TERMINAL_KINDS for e in evs):
            self._close(rid)
        return len(evs)

    # -- reading / export --------------------------------------------------

    def events(self):
        return list(self._events)

    def tail(self, n=1000):
        """The newest `n` events (the postmortem-bundle slice)."""
        if n >= len(self._events):
            return list(self._events)
        return list(self._events)[-int(n):]

    def trail(self, rid):
        """Every event of request `rid` in order ([] when unknown or
        evicted)."""
        return list(self._trails.get(rid, ()))

    def trails(self):
        """rids with a retained trail."""
        return list(self._trails)

    def __len__(self):
        return len(self._events)

    def clear(self):
        self._events.clear()
        self._trails.clear()
        self._closed.clear()
        self._seq = 0
        self.dropped = 0
        self.trail_evictions = 0

    def to_jsonl(self, events=None):
        """One JSON object per line (default=str: a non-serializable
        field degrades to its repr, never breaks the export)."""
        evs = self._events if events is None else events
        return ''.join(json.dumps(e, default=str) + '\n' for e in evs)

    def save(self, path, tail=None):
        """Write journal.jsonl (optionally only the newest `tail`
        events) and return the path."""
        evs = None if tail is None else self.tail(tail)
        with open(path, 'w') as f:
            f.write(self.to_jsonl(evs))
        return path


JOURNAL = Journal()


# -- module-level conveniences over the global journal ----------------------

# `record` is THE hot call (serving marks ride it several times per
# scheduler step), so it is the bound method itself — no wrapper frame.
# JOURNAL is never replaced (clear() resets it in place), so the
# binding stays valid for the life of the process.
record = JOURNAL.record


def trail(rid):
    return JOURNAL.trail(rid)


def save(path, tail=None):
    return JOURNAL.save(path, tail=tail)


def tail(n=1000):
    return JOURNAL.tail(n)


# -- trail analysis (shared by tests, the bench gate, and the CLI) ----------

def strip_times(events):
    """Events minus the TIME_FIELDS — the determinism-comparable form."""
    return [{k: v for k, v in e.items() if k not in TIME_FIELDS}
            for e in events]


def trail_complete(events, state=None):
    """Problems with one request trail (empty list = complete and
    ordered): non-empty, seq strictly increasing, starts at 'arrival',
    ends at a terminal kind (matching `state` when given, e.g. the
    engine's `status(rid)`)."""
    problems = []
    if not events:
        return ['empty trail']
    kinds = [e.get('kind') for e in events]
    seqs = [e.get('seq') for e in events]
    if kinds[0] != 'arrival':
        problems.append(f"starts at {kinds[0]!r}, not 'arrival'")
    if any(s is None for s in seqs) or any(
            b <= a for a, b in zip(seqs, seqs[1:])):
        problems.append('seq not strictly increasing')
    if kinds[-1] not in TERMINAL_KINDS:
        problems.append(f'last event {kinds[-1]!r} is not terminal')
    elif state is not None and kinds[-1] != state:
        problems.append(
            f'terminal event {kinds[-1]!r} != request state {state!r}')
    return problems
