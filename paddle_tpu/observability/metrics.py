"""MetricsRegistry — process-global host-side serving/training metrics.

The telemetry the production SLOs actually track (TTFT, p99 inter-token
latency, queue wait — ROADMAP item 2) is HOST truth: request arrival
and commit times, scheduler decisions, pool occupancy. None of it needs
a device sync, so this module is deliberately dependency-free (no jax,
no numpy) and every record is a few dict operations — cheap enough to
live inside the serving loop's one-host-sync-per-window commit points
without moving the tok/s needle (bench.py's
`gate_observability_overhead` holds it within 3%).

Three metric kinds, the Prometheus trio:

  - `Counter`   — monotonically increasing (tokens, admissions,
                  preemptions, compile events);
  - `Gauge`     — last-write-wins level (queue depth, pool bytes in
                  use, tokens/s over the last window);
  - `Histogram` — FIXED bucket boundaries chosen at creation, with
                  p50/p95/p99 estimated from the bucket counts by
                  linear interpolation (ttft_ms, itl_ms,
                  queue_wait_ms). Fixed buckets keep `observe()` O(len
                  buckets) with zero allocation — no reservoir, no
                  sorting, bounded memory for a server that runs for
                  weeks.

One process-global `REGISTRY` (module-level, like
inference.engine.COMPILE_CACHE) so the engines, the dataloader, and
bench.py all see one namespace; `snapshot()` is the JSON artifact and
`to_prometheus()` the text exposition a scrape endpoint would serve.

The whole subsystem is switchable: `set_enabled(False)` (or env
`PADDLE_TPU_TELEMETRY=0`) turns every mutating call into an early
return, which is what the bench overhead gate diffs against.
"""
from __future__ import annotations

import json
import math
import os
import threading

__all__ = [
    'Counter', 'Gauge', 'Histogram', 'MetricsRegistry', 'REGISTRY',
    'enabled', 'set_enabled', 'DEFAULT_MS_BUCKETS', 'inc', 'set_gauge',
    'observe',
]

_ENABLED = os.environ.get('PADDLE_TPU_TELEMETRY', '1') != '0'


def enabled():
    """Whether telemetry recording is on (default yes; env
    PADDLE_TPU_TELEMETRY=0 or set_enabled(False) turns it off)."""
    return _ENABLED


def set_enabled(on):
    """Flip recording globally. Off turns every counter/gauge/histogram
    mutation AND every tracer span into a no-op — the state the bench
    overhead gate measures against."""
    global _ENABLED
    _ENABLED = bool(on)


# latency buckets in MILLISECONDS: sub-ms host work through multi-second
# cold compiles. The +inf bucket is implicit (Histogram adds it).
DEFAULT_MS_BUCKETS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
    500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0,
)


class Counter:
    """Monotonic counter. `inc(n)` with n < 0 raises — a decreasing
    counter is a bug worth failing on, not silently recording."""

    kind = 'counter'

    def __init__(self, name, help=''):
        self.name = name
        self.help = help
        self.value = 0

    def inc(self, n=1):
        if not _ENABLED:
            return
        if n < 0:
            raise ValueError(f'counter {self.name}: inc({n}) < 0')
        self.value += n

    def snapshot(self):
        return {'type': 'counter', 'value': self.value}


class Gauge:
    """Last-write-wins level; None until first set."""

    kind = 'gauge'

    def __init__(self, name, help=''):
        self.name = name
        self.help = help
        self.value = None

    def set(self, v):
        if not _ENABLED:
            return
        self.value = float(v)

    def snapshot(self):
        return {'type': 'gauge', 'value': self.value}


class Histogram:
    """Fixed-bucket histogram with percentile snapshots.

    `buckets` are UPPER bucket edges (ascending); an implicit +inf
    bucket catches the tail. `percentile(p)` walks the cumulative
    counts to the target rank and linearly interpolates inside the
    landing bucket (the first bucket interpolates from the observed
    min, the +inf bucket reports the observed max) — standard
    Prometheus-style estimation, exact to bucket resolution, O(1)
    memory regardless of observation count."""

    kind = 'histogram'

    def __init__(self, name, buckets=None, help=''):
        self.name = name
        self.help = help
        edges = tuple(sorted(float(b) for b in
                             (buckets or DEFAULT_MS_BUCKETS)))
        if not edges:
            raise ValueError(f'histogram {self.name}: empty buckets')
        self.edges = edges
        self.counts = [0] * (len(edges) + 1)   # [+inf] is the last slot
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, v, n=1):
        """Record `n` observations of value `v` (n > 1 is the window
        commit shape: every token in a decode window shares one
        measured per-token latency)."""
        if not _ENABLED or n < 1:
            return
        v = float(v)
        if math.isnan(v):
            return
        lo, hi = 0, len(self.edges)            # bisect over the edges
        while lo < hi:
            mid = (lo + hi) // 2
            if v <= self.edges[mid]:
                hi = mid
            else:
                lo = mid + 1
        self.counts[lo] += n
        self.count += n
        self.sum += v * n
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def percentile(self, p):
        """Estimated p-th percentile (p in [0, 100]); None when empty."""
        if self.count == 0:
            return None
        rank = (p / 100.0) * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            prev_cum = cum
            cum += c
            if cum >= rank:
                if i == len(self.edges):       # +inf bucket: observed max
                    return self.max
                lo = self.edges[i - 1] if i > 0 else (self.min or 0.0)
                hi = self.edges[i]
                lo = max(lo, self.min if self.min is not None else lo)
                hi = min(hi, self.max if self.max is not None else hi)
                if hi <= lo:
                    return hi
                frac = (rank - prev_cum) / c
                return lo + (hi - lo) * max(0.0, min(1.0, frac))
        return self.max

    def snapshot(self):
        return {
            'type': 'histogram',
            'count': self.count,
            'sum': round(self.sum, 6),
            'mean': round(self.sum / self.count, 6) if self.count else None,
            'min': self.min,
            'max': self.max,
            'p50': self.percentile(50),
            'p95': self.percentile(95),
            'p99': self.percentile(99),
        }


class MetricsRegistry:
    """Name -> metric map with get-or-create accessors (call sites
    always go through the registry, so a `reset()` mid-flight never
    strands a stale metric object in an engine)."""

    def __init__(self):
        self._metrics: dict = {}
        self._lock = threading.Lock()
        # bumped on every reset(): hot paths may CACHE metric handles
        # keyed on this, so a reset invalidates their cache instead of
        # stranding writes on orphaned objects
        self.generation = 0

    def _get(self, name, cls, **kw):
        m = self._metrics.get(name)
        if m is not None:
            if not isinstance(m, cls):
                raise TypeError(
                    f'metric {name!r} already registered as {m.kind}, '
                    f'requested as {cls.kind}')
            return m
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f'metric {name!r} already registered as {m.kind}, '
                    f'requested as {cls.kind}')
            return m

    def counter(self, name, help=''):
        return self._get(name, Counter, help=help)

    def gauge(self, name, help=''):
        return self._get(name, Gauge, help=help)

    def histogram(self, name, buckets=None, help=''):
        return self._get(name, Histogram, buckets=buckets, help=help)

    def get(self, name):
        """The metric object, or None (read-only lookup)."""
        return self._metrics.get(name)

    def percentile(self, name, p, round_to=2):
        """Rounded percentile of histogram `name`, or None when the
        metric is absent/empty/not a histogram — the one accessor
        bench.py and tools/telemetry_dump.py stamp artifacts from."""
        m = self._metrics.get(name)
        if not isinstance(m, Histogram):
            return None
        v = m.percentile(p)
        return round(v, round_to) if v is not None else None

    def names(self):
        # copied under the lock: readers (the ops-server scrape
        # thread, a timeseries commit) iterate concurrently with lazy
        # metric registration on the scheduler thread, and a bare
        # sorted(dict) can raise 'dictionary changed size' exactly at
        # state-transition moments (first drain refusal, first breach)
        with self._lock:
            return sorted(self._metrics)

    def reset(self):
        """Drop every metric (tests and the overhead gate isolate runs
        with this; engines re-create on next record — cached handles
        notice via `generation`)."""
        with self._lock:
            self._metrics.clear()
            self.generation += 1

    def snapshot(self):
        """{name: metric snapshot} — the telemetry.json artifact.
        The name set is copied under the lock (see `names`); the
        per-metric reads run outside it (attribute reads are
        GIL-atomic, and a concurrently-ticking counter is an ordinary
        torn-read race any snapshot accepts)."""
        with self._lock:
            metrics = dict(self._metrics)
        return {name: metrics[name].snapshot()
                for name in sorted(metrics)}

    def to_json(self, **kw):
        return json.dumps(self.snapshot(), **kw)

    def to_prometheus(self):
        """Prometheus text exposition (format 0.0.4). Metric names are
        sanitized (dots -> underscores) to the legal charset — with
        COLLIDING sanitizations disambiguated per `_prom_names` so two
        distinct registry names can never emit duplicate series;
        `# HELP` text is spec-escaped (backslash, newline) and
        `# TYPE`/`# HELP` headers are emitted at most once per
        exposition name; histogram buckets emit cumulative
        `_bucket{le=...}` rows plus `_sum` and `_count`, the standard
        shape scrapers expect."""
        lines = []
        # copied under the lock: the ops-server scrape runs on its own
        # thread while the scheduler lazily registers metrics
        with self._lock:
            metrics = dict(self._metrics)
        names = sorted(metrics)
        pnames = _prom_names(metrics)
        emitted = set()
        for name in names:
            m = metrics[name]
            pname = pnames[name]
            if pname not in emitted:
                emitted.add(pname)
                if m.help:
                    lines.append(
                        f'# HELP {pname} {_prom_escape_help(m.help)}')
                lines.append(f'# TYPE {pname} {m.kind}')
            if m.kind == 'counter':
                lines.append(f'{pname} {m.value}')
            elif m.kind == 'gauge':
                v = m.value if m.value is not None else float("nan")
                lines.append(f'{pname} {v}')
            else:
                cum = 0
                for edge, c in zip(m.edges, m.counts):
                    cum += c
                    lines.append(
                        f'{pname}_bucket{{le="{edge}"}} {cum}')
                lines.append(
                    f'{pname}_bucket{{le="+Inf"}} {m.count}')
                lines.append(f'{pname}_sum {m.sum}')
                lines.append(f'{pname}_count {m.count}')
        return '\n'.join(lines) + ('\n' if lines else '')


def _prom_name(name):
    out = []
    for i, ch in enumerate(name):
        ok = ch.isascii() and (ch.isalpha() or ch == '_'
                               or (ch.isdigit() and i > 0))
        out.append(ch if ok else '_')
    return ''.join(out)


def _prom_escape_help(text):
    """Spec escaping for `# HELP` text (exposition format 0.0.4):
    backslash first, then newline — unescaped, a multi-line help
    string would inject arbitrary exposition rows."""
    return str(text).replace('\\', r'\\').replace('\n', r'\n')


_COLLISIONS_WARNED: set = set()


def _prom_claims(pname, kind):
    """Every exposition series name one metric emits: histograms own
    their `_bucket`/`_sum`/`_count` suffix rows too, so a counter
    literally named `x_count` collides with histogram `x` even though
    their BASE names differ."""
    if kind == 'histogram':
        return (pname, f'{pname}_bucket', f'{pname}_sum',
                f'{pname}_count')
    return (pname,)


def _prom_names(metrics):
    """Map each registry name to a UNIQUE exposition name. Sanitizing
    is lossy ('serve.tok/s' and 'serve.tok_s' both become
    'serve_tok_s'), and two distinct metrics sharing one exposition
    series name silently emit duplicate samples — the scrape keeps
    only one, whichever sorts last. Collisions are judged over every
    series a metric EMITS (`_prom_claims`, so histogram suffix rows
    count); every collider gets an 8-hex blake2b suffix of its RAW
    name — a function of the name alone, so the mapping is
    deterministic across processes and registration orders — and each
    collision warns once per process. Takes the registry's
    name -> metric dict."""
    import hashlib
    import warnings

    sanitized = {n: _prom_name(n) for n in metrics}
    owners: dict = {}
    for n, pn in sanitized.items():
        for claim in _prom_claims(pn, metrics[n].kind):
            owners.setdefault(claim, []).append(n)
    colliding = {n for names in owners.values()
                 if len(names) > 1 for n in names}
    out = {}
    for n, pn in sanitized.items():
        if n in colliding:
            suffix = hashlib.blake2b(n.encode(),
                                     digest_size=4).hexdigest()
            out[n] = f'{pn}_{suffix}'
            if pn not in _COLLISIONS_WARNED:
                _COLLISIONS_WARNED.add(pn)
                group = sorted({
                    r for claim in _prom_claims(pn, metrics[n].kind)
                    for r in owners.get(claim, ())
                    if len(owners[claim]) > 1})
                warnings.warn(
                    f'metric names {group} collide after Prometheus '
                    f'sanitization (around {pn!r}); disambiguating '
                    f'every collider with a name-hash suffix — rename '
                    f'the metrics to avoid the collision',
                    RuntimeWarning, stacklevel=3)
        else:
            out[n] = pn
    return out


REGISTRY = MetricsRegistry()


# -- module-level conveniences (the form the engines use: one call, no
# held metric object, registry lookup each time so reset() is safe) ----

def inc(name, n=1, help=''):
    if not _ENABLED:
        return
    REGISTRY.counter(name, help=help).inc(n)


def set_gauge(name, v, help=''):
    if not _ENABLED:
        return
    REGISTRY.gauge(name, help=help).set(v)


def observe(name, v, n=1, buckets=None, help=''):
    if not _ENABLED:
        return
    REGISTRY.histogram(name, buckets=buckets, help=help).observe(v, n=n)
