"""Ops HTTP endpoint — scrape, health, and status for one process.

The operational surface every production serving stack exposes
(vLLM/SGLang ship the same shape) and ROADMAP item 1's fleet needs: a
router polls `/healthz` to route around a sick replica, Prometheus
scrapes `/metrics`, an operator curls `/statusz` before deciding
whether to drain. Opt-in and stdlib-only: `ThreadingHTTPServer` on a
daemon thread, no framework, no jax at import, started either by
`ServingEngine(ops_port=...)` or standalone:

    srv = start_ops_server(engine, port=9100)   # port 0 = ephemeral
    ...
    srv.close()

Endpoints (GET only):

    /metrics   Prometheus text exposition of the process registry
               (includes the windowed-rate gauges `serve.tok_s` etc.
               the timeseries publishes) — text/plain 0.0.4;
    /healthz   the watchdog verdict as JSON: 200 when healthy, 503
               when any SLO rule is in breach — and DRAIN-AWARE: a
               draining engine answers 503 `{"status": "draining"}`
               regardless of rule state, so a rolling restart stops
               routing before the snapshot is cut. No watchdog
               configured = 200 with `"watchdog": false` (liveness
               only);
    /statusz   one JSON page of engine truth: `engine.stats()`,
               geometry, the dispatch-cost table, the journal tail,
               and the recent timeseries windows;
    /slo       per-rule config + live state (`Watchdog.state()`).

Consistency contract: handlers run on the server thread while the
scheduler mutates host state, protected by the GIL but NOT by a lock
— a read is a best-effort point-in-time view (a torn `stats()` read
retries, then reports 500). That is the right trade: serving never
blocks on a scrape, and scrapers tolerate a failed poll.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import journal as _journal
from . import metrics as _metrics

__all__ = ['OpsServer', 'start_ops_server']


class OpsServer:
    """The background ops endpoint. Resolves its data sources once at
    construction: the process registry, plus — when an engine is
    given — that engine's timeseries, watchdog, drain flag, stats and
    dispatch costs."""

    def __init__(self, engine=None, *, host='127.0.0.1', port=0,
                 registry=None, timeseries=None, watchdog=None,
                 journal=None, journal_tail=200, ts_tail=30):
        self.engine = engine
        self.registry = registry if registry is not None else _metrics.REGISTRY
        # whose flight recorder /statusz tails — a private-registry
        # replica passes its private journal so N per-replica ops
        # endpoints on one host never interleave each other's events
        self.journal = journal if journal is not None else _journal.JOURNAL
        self.timeseries = (timeseries if timeseries is not None
                           else getattr(engine, '_ts', None))
        self.watchdog = (watchdog if watchdog is not None
                         else getattr(engine, '_watchdog', None))
        self.journal_tail = int(journal_tail)
        self.ts_tail = int(ts_tail)
        self.host = host
        ops = self

        class Handler(BaseHTTPRequestHandler):
            # scrapes arrive every few seconds forever; logging each
            # to stderr is noise the serving logs cannot afford
            def log_message(self, fmt, *args):      # noqa: ARG002
                pass

            def do_GET(self):                        # noqa: N802
                try:
                    ops._route(self)
                except BrokenPipeError:
                    pass                             # client went away
                except Exception as e:  # noqa: BLE001 - a scrape must
                    #   never kill the server thread; report and move on
                    try:
                        ops._send(self, 500,
                                  {'error': repr(e)})
                    except Exception:   # noqa: BLE001
                        pass

        self._server = ThreadingHTTPServer((host, int(port)), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f'paddle-tpu-ops:{self.port}', daemon=True)
        self._thread.start()

    # -- plumbing ----------------------------------------------------------

    def url(self, path='/'):
        return f'http://{self.host}:{self.port}{path}'

    def close(self):
        """Stop the server and join its thread (idempotent)."""
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._thread.join(timeout=5)

    @staticmethod
    def _send(handler, code, payload, content_type='application/json'):
        if isinstance(payload, str):
            body = payload.encode()
        else:
            body = json.dumps(payload, default=repr).encode()
        handler.send_response(code)
        handler.send_header('Content-Type', content_type)
        handler.send_header('Content-Length', str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def _route(self, handler):
        path = handler.path.split('?', 1)[0].rstrip('/') or '/'
        if path == '/metrics':
            self._send(handler, 200, self.registry.to_prometheus(),
                       content_type='text/plain; version=0.0.4; '
                                    'charset=utf-8')
        elif path == '/healthz':
            code, payload = self.health()
            self._send(handler, code, payload)
        elif path == '/statusz':
            self._send(handler, 200, self.statusz())
        elif path == '/slo':
            if self.watchdog is None:
                self._send(handler, 404, {'error': 'no watchdog '
                                                   'configured'})
            else:
                self._send(handler, 200,
                           {'verdict': self.watchdog.verdict(),
                            'rules': self.watchdog.state()})
        else:
            self._send(handler, 404, {'error': f'unknown path {path!r}',
                                      'paths': ['/metrics', '/healthz',
                                                '/statusz', '/slo']})

    # -- verdicts (also callable in-process, no HTTP round trip) -----------

    def health(self):
        """(status_code, payload) for /healthz. Drain wins over rule
        state: a draining replica must fall out of the router NOW even
        if every SLO is green."""
        role = getattr(self.engine, 'phase_role', 'monolithic')
        if getattr(self.engine, 'draining', False):
            return 503, {'status': 'draining', 'phase_role': role}
        if self.watchdog is None:
            return 200, {'status': 'ok', 'watchdog': False,
                         'phase_role': role}
        v = self.watchdog.verdict()
        if v['healthy']:
            return 200, {'status': 'ok', 'phase_role': role, **v}
        return 503, {'status': 'breach', 'phase_role': role, **v}

    def statusz(self):
        payload = {}
        if self.engine is not None:
            # the scheduler may mutate mid-read (GIL-safe, not
            # lock-safe): one retry absorbs the torn iteration, a
            # second failure reports instead of raising
            for _ in range(2):
                try:
                    payload['engine'] = self.engine.stats()
                    break
                except RuntimeError:
                    continue
            else:
                payload['engine'] = {'error': 'stats() contended'}
            costs = getattr(self.engine, '_dispatch_costs', None)
            if costs:
                payload['dispatch_costs'] = {str(k): v
                                             for k, v in costs.items()}
            payload['draining'] = bool(getattr(self.engine, 'draining',
                                               False))
            payload['phase_role'] = getattr(self.engine, 'phase_role',
                                            'monolithic')
        if self.timeseries is not None:
            payload['timeseries'] = {
                'interval_s': self.timeseries.interval_s,
                'windows': self.timeseries.windows(self.ts_tail)}
        if self.watchdog is not None:
            payload['watchdog'] = self.watchdog.verdict()
        payload['journal_tail'] = self.journal.tail(self.journal_tail)
        return payload


def start_ops_server(engine=None, port=0, host='127.0.0.1', **kw):
    """Start the ops endpoint for `engine` (or a bare metrics/health
    endpoint with no engine). Returns the running OpsServer; `port=0`
    binds an ephemeral port (read `.port`). The server thread is a
    daemon — it dies with the process — but long-lived callers should
    `close()` it deterministically."""
    return OpsServer(engine, host=host, port=port, **kw)
