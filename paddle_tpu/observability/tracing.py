"""HostTracer — Chrome/Perfetto `trace_event` spans for host-side
scheduler decisions.

`jax.profiler` captures the DEVICE timeline (XLA ops, DMA, compiles as
XLA sees them) but the host half of serving — admission decisions,
preemptions, window dispatch cadence, CompileCache misses — is
invisible there. This tracer records those as standard Chrome
trace_event JSON (`ph: "X"` complete spans and `ph: "i"` instants), so
`host_trace.json` loads in Perfetto / chrome://tracing directly and can
sit in the same UI session as a jax.profiler device trace
(docs/observability.md shows the overlay recipe).

Design constraints, same discipline as the metrics registry:

  - host-only: recording is an append of one small dict; NOTHING here
    touches the device or forces a sync;
  - bounded: a ring of `max_events` (default 100k) so a server that
    runs for weeks cannot leak the host heap — overflow drops the
    OLDEST events and counts `dropped`;
  - switchable: every record checks `metrics.enabled()`, so the bench
    overhead gate's telemetry-off run skips this too.

`annotate(name)` is the dual-timeline bridge: one context manager that
opens a host span here AND a `jax.profiler.TraceAnnotation` on the XLA
timeline (profiler.RecordEvent routes through it), so a single API call
marks both traces with the same name.
"""
from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time

from . import metrics as _metrics

__all__ = ['HostTracer', 'TRACER', 'span', 'instant', 'compile_event',
           'annotate', 'export', 'save', 'to_chrome_trace']

# one process-wide epoch so every event's ts is comparable; perf_counter
# is monotonic (wall-clock jumps cannot reorder spans)
_EPOCH = time.perf_counter()


def _now_us():
    return (time.perf_counter() - _EPOCH) * 1e6


class _Span:
    """Open span handle: context manager OR explicit begin()/end()
    (profiler.RecordEvent needs the latter). A span created while
    telemetry is disabled is inert."""

    __slots__ = ('_tracer', 'name', 'cat', 'args', '_t0')

    def __init__(self, tracer, name, cat, args):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = None

    def begin(self):
        if _metrics.enabled():
            self._t0 = _now_us()
        return self

    def end(self):
        if self._t0 is not None:
            self._tracer._emit(self.name, self.cat, self._t0,
                               _now_us() - self._t0, self.args)
            self._t0 = None

    def __enter__(self):
        return self.begin()

    def __exit__(self, *exc):
        self.end()
        return False


class HostTracer:
    """Bounded host-side trace_event recorder."""

    def __init__(self, max_events=100_000):
        self.max_events = int(max_events)
        self._events: collections.deque = collections.deque(
            maxlen=self.max_events)
        self.dropped = 0
        self._pid = os.getpid()

    # -- recording ---------------------------------------------------------

    def _emit(self, name, cat, ts, dur, args, ph='X'):
        ev = {'name': name, 'cat': cat, 'ph': ph, 'ts': ts,
              'pid': self._pid, 'tid': threading.get_ident() % 2**31}
        if ph == 'X':
            ev['dur'] = dur
        elif ph == 'i':
            ev['s'] = 'p'
        if args:
            ev['args'] = args
        if len(self._events) == self.max_events:
            # silent event loss is itself an observability bug: surface
            # ring overflow as a registry counter so dashboards see a
            # truncated trace for what it is
            self.dropped += 1
            _metrics.inc('trace.dropped_events')
        self._events.append(ev)

    def span(self, name, cat='host', **args):
        """Context manager (or begin()/end() handle) recording one
        complete span on exit."""
        return _Span(self, name, cat, args)

    def instant(self, name, cat='host', **args):
        if not _metrics.enabled():
            return
        self._emit(name, cat, _now_us(), 0.0, args, ph='i')

    def compile_event(self, name, key=None, dur_s=None, **args):
        """One compile/retrace event on the `compile` track. With a
        wall duration it renders as a span covering the compiling
        dispatch; without one (a bare retrace count tick) it is an
        instant."""
        if not _metrics.enabled():
            return
        if key is not None:
            args['key'] = str(key)
        if dur_s is None:
            self._emit(name, 'compile', _now_us(), 0.0, args, ph='i')
        else:
            dur_us = float(dur_s) * 1e6
            self._emit(name, 'compile', _now_us() - dur_us, dur_us, args)

    # -- reading / export --------------------------------------------------

    def events(self):
        return list(self._events)

    def __len__(self):
        return len(self._events)

    def clear(self):
        self._events.clear()
        self.dropped = 0

    def to_chrome_trace(self):
        """The `trace_event` ARRAY form (what Perfetto and
        chrome://tracing both accept)."""
        return self.events()

    def to_json(self, **kw):
        # default=str: span args are caller-supplied (annotate(**args))
        # and a non-serializable arg must degrade to its repr, never
        # make the export raise
        kw.setdefault('default', str)
        return json.dumps(self.to_chrome_trace(), **kw)

    def export(self, path):
        """Write host_trace.json (trace_event array) and return the
        path."""
        with open(path, 'w') as f:
            json.dump(self.to_chrome_trace(), f, default=str)
        return path

    def save(self, path):
        """`export` alias — the artifact-writing verb the registry
        (`to_json`) and journal (`save`) families use."""
        return self.export(path)


TRACER = HostTracer()


# -- module-level conveniences over the global tracer ----------------------

def span(name, cat='host', **args):
    return TRACER.span(name, cat, **args)


def instant(name, cat='host', **args):
    TRACER.instant(name, cat, **args)


def compile_event(name, key=None, dur_s=None, **args):
    TRACER.compile_event(name, key=key, dur_s=dur_s, **args)


def export(path):
    return TRACER.export(path)


def save(path):
    return TRACER.export(path)


def to_chrome_trace():
    return TRACER.to_chrome_trace()


@contextlib.contextmanager
def annotate(name, cat='host', **args):
    """The dual-timeline bridge: one `with annotate('train_step'):`
    records a host span here AND a jax.profiler.TraceAnnotation on the
    device timeline, so the two traces share a name to line up on.

    The telemetry kill switch gates only the HOST span (the recording
    this package added); the device-timeline annotation is jax's
    long-standing behavior and fires regardless, keeping every
    RecordEvent form consistent with its pre-observability semantics.
    Degrades to host-only when jax (or its profiler) is unavailable —
    annotation must never be able to break the annotated code."""
    ctx = None
    try:
        import jax

        ctx = jax.profiler.TraceAnnotation(name)
        ctx.__enter__()
    except Exception:  # noqa: BLE001 - annotation is best-effort
        ctx = None
    with TRACER.span(name, cat, **args):
        try:
            yield
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)
