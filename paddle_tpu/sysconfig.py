"""paddle_tpu.sysconfig (ref: python/paddle/sysconfig.py)."""
from __future__ import annotations

import os


def get_include():
    """ref: paddle.sysconfig.get_include — C headers directory (the
    native helpers' sources live under _native)."""
    return os.path.join(os.path.dirname(__file__), '_native')


def get_lib():
    """ref: paddle.sysconfig.get_lib — built native libraries cache."""
    return os.path.join(os.path.dirname(__file__), '_native')
