"""paddle_tpu.sysconfig (ref: python/paddle/sysconfig.py)."""
from __future__ import annotations

import os


def get_include():
    """ref: paddle.sysconfig.get_include — C headers directory (the
    native helpers' sources live under _native)."""
    return os.path.join(os.path.dirname(__file__), '_native')


def get_lib():
    """ref: paddle.sysconfig.get_lib — directory holding the BUILT
    native libraries (the same cache _native compiles into)."""
    cache = os.environ.get(
        'PADDLE_TPU_CACHE',   # the SAME var _native/__init__.py honors
        os.path.join(os.path.expanduser('~'), '.cache', 'paddle_tpu'))
    os.makedirs(cache, exist_ok=True)
    return cache


_COMPILATION_CACHE_DIR = None


def enable_persistent_compilation_cache(path=None):
    """Wire jax's on-disk executable cache so serving restarts skip XLA
    compilation entirely (the in-process jit cache only survives the
    process; this one survives reboots). Used by
    inference.engine.DecodeEngine(persistent_cache=True), by
    `paddle_tpu.aot` artifacts (build persists INTO an artifact's cache
    dir, warm-attach re-wires it), and honored directly by the
    PADDLE_TPU_PERSISTENT_CACHE env var ('1' for the default dir, any
    other non-empty value is an explicit directory).

    `path` is the explicit cache directory; an explicit path always
    wins over (and replaces) a previously wired one — an artifact
    attach must not silently keep writing into the default cache.
    Default is get_lib()/xla_cache (the same PADDLE_TPU_CACHE root the
    native helpers use). Thresholds are dropped to zero so even small
    decode-step executables persist. Idempotent; returns the cache
    directory (None if this jax build has no compilation-cache
    support).

    The wired directory is observable in the PR-6 telemetry: a
    `compile.persistent_cache_dir` instant on the host trace (with the
    path) and a `compile.persistent_cache_enabled` gauge in the
    registry, so artifact-backed runs are distinguishable from
    cold ones in every telemetry dump."""
    global _COMPILATION_CACHE_DIR
    import jax

    if path is None:
        path = _COMPILATION_CACHE_DIR or os.path.join(get_lib(), 'xla_cache')
    path = os.path.abspath(os.path.expanduser(path))
    if 'jax_compilation_cache_dir' not in jax.config.values:
        return None
    os.makedirs(path, exist_ok=True)
    jax.config.update('jax_compilation_cache_dir', path)
    for opt, val in (('jax_persistent_cache_min_compile_time_secs', 0.0),
                     ('jax_persistent_cache_min_entry_size_bytes', -1)):
        try:
            jax.config.update(opt, val)
        except Exception:  # noqa: BLE001 - older jax: keep its defaults
            pass
    _COMPILATION_CACHE_DIR = path
    # jax freezes its is-the-cache-used verdict at the FIRST compile of
    # the process; wiring a directory after any compile (engine
    # construction alone compiles helpers) would silently never
    # persist. reset_cache() clears that verdict so the next compile
    # re-evaluates against the directory just wired.
    try:
        from jax._src.compilation_cache import reset_cache

        reset_cache()
    except Exception:  # noqa: BLE001 - private API moved: best effort
        pass
    from .observability import metrics as _obs
    from .observability import tracing as _obs_trace

    _obs.set_gauge('compile.persistent_cache_enabled', 1.0)
    _obs_trace.instant('compile.persistent_cache_dir', cat='compile',
                       path=path)
    return path


def persistent_compilation_cache_dir():
    """The directory enable_persistent_compilation_cache wired (None if
    never enabled this process)."""
    return _COMPILATION_CACHE_DIR


def restore_persistent_compilation_cache(path):
    """Re-wire the persistent cache to `path`, or fully UNWIRE it when
    `path` is None — the restore half of a scoped redirection (aot.build
    points the cache at an artifact directory for the duration of the
    build only; leaving it wired would leak every later compile of a
    still-serving builder into the artifact, and starve whatever dir
    the process had wired before)."""
    global _COMPILATION_CACHE_DIR
    if path is not None:
        return enable_persistent_compilation_cache(path)
    import jax

    _COMPILATION_CACHE_DIR = None
    if 'jax_compilation_cache_dir' in jax.config.values:
        jax.config.update('jax_compilation_cache_dir', None)
    return None
