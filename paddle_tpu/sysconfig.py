"""paddle_tpu.sysconfig (ref: python/paddle/sysconfig.py)."""
from __future__ import annotations

import os


def get_include():
    """ref: paddle.sysconfig.get_include — C headers directory (the
    native helpers' sources live under _native)."""
    return os.path.join(os.path.dirname(__file__), '_native')


def get_lib():
    """ref: paddle.sysconfig.get_lib — directory holding the BUILT
    native libraries (the same cache _native compiles into)."""
    cache = os.environ.get(
        'PADDLE_TPU_CACHE',   # the SAME var _native/__init__.py honors
        os.path.join(os.path.expanduser('~'), '.cache', 'paddle_tpu'))
    os.makedirs(cache, exist_ok=True)
    return cache


_COMPILATION_CACHE_DIR = None


def enable_persistent_compilation_cache(path=None):
    """Wire jax's on-disk executable cache so serving restarts skip XLA
    compilation entirely (the in-process jit cache only survives the
    process; this one survives reboots). Used by
    inference.engine.DecodeEngine(persistent_cache=True) and honored
    directly by `PADDLE_TPU_PERSISTENT_CACHE=1`.

    Stores under get_lib()/xla_cache by default (the same
    PADDLE_TPU_CACHE root the native helpers use). Thresholds are
    dropped to zero so even small decode-step executables persist.
    Idempotent; returns the cache directory (None if this jax build has
    no compilation-cache support)."""
    global _COMPILATION_CACHE_DIR
    import jax

    if path is None:
        path = _COMPILATION_CACHE_DIR or os.path.join(get_lib(), 'xla_cache')
    if 'jax_compilation_cache_dir' not in jax.config.values:
        return None
    os.makedirs(path, exist_ok=True)
    jax.config.update('jax_compilation_cache_dir', path)
    for opt, val in (('jax_persistent_cache_min_compile_time_secs', 0.0),
                     ('jax_persistent_cache_min_entry_size_bytes', -1)):
        try:
            jax.config.update(opt, val)
        except Exception:  # noqa: BLE001 - older jax: keep its defaults
            pass
    _COMPILATION_CACHE_DIR = path
    return path


def persistent_compilation_cache_dir():
    """The directory enable_persistent_compilation_cache wired (None if
    never enabled this process)."""
    return _COMPILATION_CACHE_DIR
