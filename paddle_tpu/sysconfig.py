"""paddle_tpu.sysconfig (ref: python/paddle/sysconfig.py)."""
from __future__ import annotations

import os


def get_include():
    """ref: paddle.sysconfig.get_include — C headers directory (the
    native helpers' sources live under _native)."""
    return os.path.join(os.path.dirname(__file__), '_native')


def get_lib():
    """ref: paddle.sysconfig.get_lib — directory holding the BUILT
    native libraries (the same cache _native compiles into)."""
    cache = os.environ.get(
        'PADDLE_TPU_CACHE',   # the SAME var _native/__init__.py honors
        os.path.join(os.path.expanduser('~'), '.cache', 'paddle_tpu'))
    os.makedirs(cache, exist_ok=True)
    return cache
