"""paddle_tpu.version (ref: python/paddle/version) — build metadata."""
from __future__ import annotations

# single source of truth: the package __version__ (defined before this
# module is imported by paddle_tpu/__init__.py)
from paddle_tpu import __version__ as full_version

major, minor, patch = (full_version.split('.') + ['0', '0', '0'])[:3]
rc = '0'
commit = 'tpu-native'
cuda_version = 'False'       # the reference reports the CUDA toolkit; N/A
cudnn_version = 'False'
istaged = False
with_pip_cuda_libraries = 'OFF'
xpu_version = 'False'


def show():
    """ref: paddle.version.show()."""
    print(f'full_version: {full_version}')
    print(f'major: {major}')
    print(f'minor: {minor}')
    print(f'patch: {patch}')
    print(f'commit: {commit}')
    print('backend: XLA:TPU (jax)')


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version


def xpu():
    return xpu_version


def nccl():
    return 'False'


def cinn():
    return 'False'
