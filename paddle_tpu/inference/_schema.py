"""The ONE place the snapshot / KV-migration wire versions live.

`ServingEngine.snapshot()` / `restore()`, `export_kv` / `import_kv`,
and `pack_kv_blob` / `unpack_kv_blob` used to each carry their own
literal `1` — four writers and four readers that had to drift together
by reviewer discipline. They all import from here now, and statelint
(analysis/state) reads the same constants for its ST003/ST004 wire
checks, so a version bump is one edit that every producer, consumer,
and prover sees at once.

Bumping a version is a WIRE change: old snapshots/blobs refuse to load
by design (the readers name the version they got vs the one they
read). Schema-1-compatible additions — new optional keys read with
`.get()` defaults, like 'draining' or a watchdog's 'last_window_idx' —
do NOT bump these; that forward-compatibility contract is what keeps a
rolling fleet upgrade from stranding every in-flight snapshot.
"""
from __future__ import annotations

# ServingEngine.snapshot()/restore() top-level schema, ALSO the schema
# of an export_kv blob dict (one versioning story: a blob survives
# exactly the process boundaries a snapshot does), a Watchdog's
# snapshot_state(), and a DisaggPair's composed pair snapshot.
SNAPSHOT_SCHEMA = 1

# the 'kind' tag distinguishing a KV-migration blob dict from a full
# engine snapshot (both carry SNAPSHOT_SCHEMA)
KV_BLOB_KIND = 'kv_migration'

# pack_kv_blob / unpack_kv_blob byte framing: 4-byte preamble magic,
# JSON header magic string, and the header's own version field
PTKV_MAGIC = b'PTKV'
PTKV_HEADER_MAGIC = 'paddle_tpu.kv_migration'
PTKV_VERSION = 1
