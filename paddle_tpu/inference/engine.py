"""DecodeEngine — the compiled serving hot path (ref: the reference
serving runtime's executor: "async dispatch is native"; here the same
property is won by never leaving compiled code between tokens).

Why an engine instead of model.generate(): the mixin loops re-trace
their scan on every call (and the speculative loops used to define
their @jax.jit closures INSIDE the loop function — a guaranteed fresh
trace per invocation). This module owns the serving path end to end:

  1. Persistent compiled-function cache. Every jitted step lives at
     MODULE level, so jax's trace cache is keyed on
     (model pytree structure, cache shapes/dtypes, static sampling
     config) and survives across calls, engines, and requests. The
     `CompileCache` registry records those keys and a per-function
     retrace counter (`trace_counts()`), so steady-state can be
     ASSERTED to be 0 retraces (bench.py does). With
     persistent_cache=True (or PADDLE_TPU_PERSISTENT_CACHE=1) the
     compiled executables also go to disk via
     sysconfig.enable_persistent_compilation_cache, surviving process
     restarts.

  2. Buffer donation. Prefill, the decode loop, and both speculative
     window functions donate their KV-cache arguments
     (`donate_argnames`), so XLA updates the cache IN PLACE instead of
     copying (B, max_len, Hkv, D) per step. Contract: a cache passed to
     an engine step is dead to the caller — see
     docs/decode_engine.md.

  3. Bucketed prefill. Prompt lengths are padded LEFT to a small set of
     power-of-two buckets; the real length rides in as a DEVICE scalar
     (positions / kv_start are computed from it inside the trace), so
     every prompt length in a bucket reuses one compilation. Tokens are
     bit-identical to unpadded prefill: pad rows are excluded by
     kv_start (per-row window start — the fused decode kernel's scalar-
     prefetch path, ops/pallas/decode_attention.py) at prefill and at
     every later step.

  4. Fused speculative windows. Each window runs draft-propose (a
     lax.scan over k+1 steps), target-verify, and the greedy commit
     rule on device; batch-1 goes further and runs the WHOLE window
     loop inside one compiled lax.while_loop (_spec_decode_b1), so a
     generate_speculative call is one dispatch and ONE host sync total.
     Batched rows commit at per-row offsets and sync once per window
     (_spec_window_batched). The models/generation.py loops delegate
     here, so the public generate_speculative API gets the same
     steady-state-0-retrace property.

Single-token decode steps route through the fused pallas decode kernel
(ops/pallas/decode_attention.py's dispatcher) via the model's
cached_attention, exactly like model.generate().
"""
from __future__ import annotations

import ast
import collections
import functools
import inspect
import itertools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import journal as _journal
from ..observability import metrics as _obs
from ..observability import tracing as _obs_trace

# ---------------------------------------------------------------------------
# Compile accounting: retrace counters + the keyed registry
# ---------------------------------------------------------------------------

_TRACE_COUNTS: collections.Counter = collections.Counter()


def _count_trace(name):
    """Called from INSIDE to-be-jitted python bodies: runs only while
    tracing, so the counter is exactly the number of (re)compilations.
    Each firing is also a `compile.traces` tick in the process-global
    metrics registry, a `trace:<name>` instant on the host trace, and a
    `trace` flight-recorder event (observability's compile/retrace
    accounting)."""
    _TRACE_COUNTS[name] += 1
    _obs.inc('compile.traces')
    _obs_trace.compile_event(f'trace:{name}')
    _journal.record('trace', fn=name)


def trace_counts():
    """Per-function trace counts since process start (or the last
    reset): {'prefill': 2, 'decode_loop': 1, ...}."""
    return dict(_TRACE_COUNTS)


def total_traces():
    return sum(_TRACE_COUNTS.values())


def reset_trace_counts():
    _TRACE_COUNTS.clear()


def model_tag(model):
    """Stable, serializable identity for a model CLASS: the qualified
    import path. Replaces the old `id(type(model))` key component —
    object ids are neither stable across processes nor serializable,
    which the AOT artifact manifest (paddle_tpu.aot) needs them to be."""
    t = type(model)
    return f'{t.__module__}.{t.__qualname__}'


def model_struct(model):
    """Structural hash of a model pytree: sha256 over every array
    leaf's (shape, dtype), in tree order. Compilation keys on exactly
    this (values don't enter the HLO shape), so the AOT artifact
    config hashes it — two same-class models of different sizes must
    NOT share an artifact (every cache lookup would silently miss),
    while same-architecture checkpoints with different weights must."""
    import hashlib

    parts = []
    for leaf in jax.tree.leaves(model):
        if hasattr(leaf, 'shape') and hasattr(leaf, 'dtype'):
            parts.append(f'{tuple(leaf.shape)}:{leaf.dtype}')
        else:
            parts.append(repr(leaf))
    return hashlib.sha256('|'.join(parts).encode()).hexdigest()[:16]


def key_str(key):
    """Stable string form of a CompileCache key. Keys are tuples of
    primitives (str/int/float/bool/None, nested tuples) by contract, so
    `repr` round-trips exactly through `key_from_str` — the property the
    AOT manifest relies on to persist per-geometry keys."""
    return repr(key)


def key_from_str(s):
    """Inverse of `key_str` (ast.literal_eval: data only, no code)."""
    return ast.literal_eval(s)


class CompileCache:
    """Bookkeeping mirror of jax's jit cache for the engine functions.

    jax itself caches compiled executables keyed on (function, pytree
    structure, avals, statics); this registry records the engine-level
    key — (model-tag, model-id, cache shape, cache dtype,
    sampling-config, geometry) — for each compilation the engine
    requests, so serving code can observe hits/misses and tests can
    assert the steady state.

    Key contract (relied on by paddle_tpu.aot): every key is a tuple of
    PRIMITIVES — str/int/float/bool/None and nested tuples of the same.
    No object ids, no callables, no arrays. `key_str`/`key_from_str`
    round-trip any key through its stable string form, which is what
    the artifact manifest persists."""

    def __init__(self):
        self._keys: dict = {}
        self.hits = 0
        self.misses = 0

    def key(self, model, cache_shape, cache_dtype, sampling,
            geometry=('contiguous',)):
        # _engine_model_id is a monotonic per-process counter stamped on
        # first use — it never recycles (id(model) can, after gc) and
        # it is a PRIMITIVE, so keys stay serializable (the aot
        # manifest contract). The raw-id fallback only covers __slots__
        # models that refuse the stamp (model_tag keeps two classes'
        # ids from colliding). The counter starts at 0, so compare
        # against None (a bare `or` would throw away the first model's
        # id as falsy)
        #
        # `geometry` is the engine's batch-capacity tuple: DecodeEngine
        # passes ('contiguous', B, max_len), ServingEngine passes
        # ('paged', slots, num_blocks, block_size, max_blocks) — without
        # it a paged engine and a contiguous engine over the same model
        # and sampling config would collide on one registry key and the
        # hit/miss accounting would lie about both
        mid = getattr(model, '_engine_model_id', None)
        if mid is None:
            try:
                model._engine_model_id = mid = next(_MODEL_IDS)
            except AttributeError:
                mid = id(model)
        return (model_tag(model), mid,
                tuple(int(s) for s in cache_shape), str(cache_dtype),
                tuple(sampling), tuple(geometry))

    def note(self, key):
        if key in self._keys:
            self.hits += 1
            _obs.inc('compile.cache_hits')
            return True
        self._keys[key] = total_traces()
        self.misses += 1
        _obs.inc('compile.cache_misses')
        return False

    def keys(self):
        return list(self._keys)

    def __len__(self):
        return len(self._keys)


COMPILE_CACHE = CompileCache()

# monotonic model ids for the registry key: id(model) can be recycled
# after a served model is garbage-collected, which would let a NEW
# model's first call masquerade as a registry hit
_MODEL_IDS = itertools.count()


# ---------------------------------------------------------------------------
# Prefill buckets
# ---------------------------------------------------------------------------

# powers of two: small prompts hit small buckets; the padding overhead
# is < 2x prefill FLOPs worst-case and buys one compilation per bucket
# instead of one per prompt length
DEFAULT_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096)


def bucket_length(seq_len, buckets=None):
    """Smallest bucket >= seq_len; past the largest bucket, the next
    power of two (a rare long prompt still compiles, it just doesn't
    share)."""
    for b in (buckets or DEFAULT_BUCKETS):
        if b >= seq_len:
            return b
    b = 1
    while b < seq_len:
        b <<= 1
    return b


# ---------------------------------------------------------------------------
# Module-level compiled steps (the persistent jit cache)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, donate_argnames=('caches',))
def _prefill_exact(model, caches, ids):
    """Unpadded prefill (prompt length == bucket, or speculative loops
    which manage their own offsets). Donates the cache."""
    _count_trace('prefill')
    logits, caches = model(ids, caches=caches, cache_index=0)
    return logits[:, -1, :], caches


@functools.partial(jax.jit, donate_argnames=('caches',))
def _prefill_padded(model, caches, ids, real_len):
    """Left-padded bucketed prefill. ids is (B, Sb) with the prompt
    right-aligned; real_len (B,) rides as DEVICE data so every prompt
    length in the bucket shares this one compilation. Pad rows get
    position 0 and are excluded from all attention by kv_start (the
    per-row window start), at prefill and forever after."""
    _count_trace('prefill')
    B, Sb = ids.shape
    real_len = jnp.broadcast_to(jnp.asarray(real_len, jnp.int32), (B,))
    kv_start = Sb - real_len                               # (B,)
    positions = jnp.maximum(
        jnp.arange(Sb, dtype=jnp.int32)[None, :] - kv_start[:, None], 0)
    logits, caches = model(ids, caches=caches, cache_index=0,
                           positions=positions, kv_start=kv_start)
    return logits[:, -1, :], caches


@functools.partial(
    jax.jit, donate_argnames=('caches',),
    static_argnames=('max_new_tokens', 'temperature', 'top_k', 'top_p',
                     'eos_token_id', 'padded'))
def _decode_loop(model, caches, last_logits, real_len, rng_key, *,
                 max_new_tokens, temperature, top_k, top_p, eos_token_id,
                 padded):
    """The whole decode phase as ONE compiled lax.scan: sample, step the
    model over the donated cache, repeat. Write index = bucket length +
    t (static + scan counter); rope positions / kv_start come from the
    traced real_len, so one executable serves every prompt length in
    the bucket."""
    _count_trace('decode_loop')
    B = last_logits.shape[0]
    # bucket length is static: cache max_len minus the decode budget
    Sb = _cache_max_len(caches) - max_new_tokens
    real_len = jnp.broadcast_to(jnp.asarray(real_len, jnp.int32), (B,))
    kv_start = Sb - real_len

    def sample(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        from ..models.generation import filter_logits

        logits = filter_logits(logits / temperature, top_k, top_p)
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

    def step(carry, t):
        last_logits, caches, key, finished = carry
        key, sub = jax.random.split(key)
        tok = sample(last_logits, sub)
        if eos_token_id is not None:
            tok = jnp.where(finished, jnp.asarray(eos_token_id, tok.dtype),
                            tok)
            finished = finished | (tok == eos_token_id)
        extra = {}
        if padded:
            extra = dict(positions=(real_len + t)[:, None],
                         kv_start=kv_start)
        logits, caches = model(tok[:, None], caches=caches,
                               cache_index=Sb + t, **extra)
        return (logits[:, -1, :], caches, key, finished), tok

    (_, caches, _, _), tokens = jax.lax.scan(
        step, (last_logits, caches, rng_key, jnp.zeros((B,), bool)),
        jnp.arange(max_new_tokens, dtype=jnp.int32))
    return tokens.T, caches                                # (B, new), caches


def _window_b1(target, draft, tcaches, dcaches, c, L, k):
    """One speculative window, batch-1 (uniform cache_index): draft
    proposes k tokens (scan over k+1 steps so the k-th proposal's own
    kv row is written too), target verifies the whole [c, d1..dk]
    window in one forward, and the greedy commit rule (longest agreeing
    prefix) runs as a cumprod. Traced body of _spec_decode_b1's
    while_loop, kept separate as the single-window unit of the
    commit-rule contract (_commit_window is its host-side spec)."""

    def body(carry, i):
        tok, dc = carry
        logits, dc = draft(tok, caches=dc, cache_index=L + i)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        return (nxt[:, None], dc), nxt

    (_, dcaches), toks = jax.lax.scan(body, (c, dcaches),
                                      jnp.arange(k + 1))
    drafts = toks[:k, 0]                                   # (k,)
    window = jnp.concatenate([c, drafts[None, :]], axis=1)  # (1, k+1)
    tlogits, tcaches = target(window, caches=tcaches, cache_index=L)
    choices = jnp.argmax(tlogits[0], axis=-1).astype(jnp.int32)  # (k+1,)
    eq = (drafts == choices[:k]).astype(jnp.int32)
    m = jnp.sum(jnp.cumprod(eq))                           # accepted prefix
    next_c = choices[m]
    return drafts, choices, m, next_c, tcaches, dcaches


@functools.partial(jax.jit, donate_argnames=('tcaches', 'dcaches'),
                   static_argnames=('k', 'max_new_tokens', 'eos_token_id'))
def _spec_decode_b1(target, draft, tcaches, dcaches, c, L0, *, k,
                    max_new_tokens, eos_token_id):
    """The WHOLE batch-1 speculative decode as one compiled
    lax.while_loop over fused windows: the accepted length is
    data-dependent, but it only steers on-device state (committed
    length L, token count n), so nothing about it needs the host — one
    dispatch and ONE host sync per generate call, not per window.

    Each window dynamic_update_slices its full k+1 candidate tokens
    [c, d1..dk] into the output buffer at offset n and advances n by
    the accepted m+1 only, so a later window's write starts exactly
    where the rejected tail begins and overwrites it; the buffer
    carries k+1 rows of slack so the final window's full-width write
    stays in bounds (no OOB clamping, which would corrupt the tail).
    Returns (buf, n): buf[:min(n, max_new_tokens)] is the committed
    stream. Both caches are donated."""
    _count_trace('spec_decode')
    buf = jnp.zeros((max_new_tokens + k + 1,), jnp.int32)

    def cond(state):
        _, _, n, finished = state[:4]
        return (n < max_new_tokens) & ~finished

    def body(state):
        c, L, n, finished, buf, tcaches, dcaches = state
        drafts, choices, m, next_c, tcaches, dcaches = _window_b1(
            target, draft, tcaches, dcaches, c, L, k)
        committed = jnp.concatenate([c[0], drafts])        # (k+1,)
        buf = jax.lax.dynamic_update_slice(buf, committed, (n,))
        ncommit = m + 1
        if eos_token_id is not None:
            idx = jnp.arange(k + 1)
            finished = finished | jnp.any(
                (committed == eos_token_id) & (idx < ncommit))
        return (next_c[None, None], L + ncommit, n + ncommit, finished,
                buf, tcaches, dcaches)

    state = (c, jnp.asarray(L0, jnp.int32), jnp.asarray(0, jnp.int32),
             jnp.asarray(False), buf, tcaches, dcaches)
    _, _, n, _, buf, tcaches, dcaches = jax.lax.while_loop(cond, body,
                                                           state)
    return buf, n, tcaches, dcaches


@functools.partial(jax.jit, donate_argnames=('tcaches', 'dcaches'),
                   static_argnames=('k',))
def _spec_window_batched(target, draft, tcaches, dcaches, c, wp, *, k):
    """Batched speculative window: rows commit at their own per-row
    offsets (kv_write_pos), commit rule vectorised over rows. c (B, 1),
    wp (B,). Returns per-row (drafts (B,k), choices (B,k+1), m (B,),
    next_c (B,))."""
    _count_trace('spec_window')

    def body(carry, i):
        tok, dc = carry
        logits, dc = draft(tok, caches=dc, kv_write_pos=wp + i)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        return (nxt[:, None], dc), nxt

    (_, dcaches), toks = jax.lax.scan(body, (c, dcaches),
                                      jnp.arange(k + 1))
    drafts = toks[:k].T                                    # (B, k)
    window = jnp.concatenate([c, drafts], axis=1)          # (B, k+1)
    tlogits, tcaches = target(window, caches=tcaches, kv_write_pos=wp)
    choices = jnp.argmax(tlogits, axis=-1).astype(jnp.int32)  # (B, k+1)
    eq = (drafts == choices[:, :k]).astype(jnp.int32)
    m = jnp.sum(jnp.cumprod(eq, axis=1), axis=1)           # (B,)
    next_c = jnp.take_along_axis(choices, m[:, None], axis=1)[:, 0]
    return drafts, choices, m, next_c, tcaches, dcaches


def _cache_max_len(caches):
    """max_len from any cache entry ((k, v) tuples or QuantKVCache)."""
    leaf = caches[0]
    arr = leaf[0] if isinstance(leaf, tuple) else leaf.kq
    return arr.shape[1]


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

class DecodeEngine:
    """Owns the compiled serving path for one model.

    Construction pins the sampling config (it is part of the
    compilation key); `generate` then runs prefill + the scanned decode
    loop through the module-level jit cache — repeated same-bucket
    calls are ZERO retraces (see `stats()`), and the KV cache is
    donated to every step (never copied).

        engine = DecodeEngine(model, max_new_tokens=64)
        out = engine.generate(input_ids)               # ids (B, S)
        out = engine.generate_speculative(draft, ids)  # greedy, lossless

    Bucketing: prompts are left-padded to `buckets` (powers of two by
    default); models must accept `positions`/`kv_start` in their cached
    forward (the Llama family does) unless every prompt length is
    exactly a bucket boundary.

    persistent_cache=True additionally wires jax's on-disk executable
    cache (sysconfig.enable_persistent_compilation_cache) so a server
    restart skips XLA compilation; PADDLE_TPU_PERSISTENT_CACHE=1 does
    the same without code changes.
    """

    def __init__(self, model, max_new_tokens=32, temperature=0.0, top_k=0,
                 top_p=1.0, eos_token_id=None, buckets=None,
                 persistent_cache=None):
        self.model = model
        if getattr(model, '_engine_model_id', None) is None:
            try:
                model._engine_model_id = next(_MODEL_IDS)
            except AttributeError:  # __slots__ model: id(model) fallback
                pass
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos_token_id = (int(eos_token_id) if eos_token_id is not None
                             else None)
        self.buckets = tuple(sorted(buckets)) if buckets else DEFAULT_BUCKETS
        if persistent_cache is None:
            # env contract: boolean-ish values toggle the DEFAULT dir
            # ('true'/'yes'/'on' count as on — a deployment writing a
            # conventional boolean must not get a junk './true' cache
            # dir); anything else is an explicit cache DIRECTORY
            env = os.environ.get('PADDLE_TPU_PERSISTENT_CACHE', '')
            low = env.strip().lower()
            if low in ('', '0', 'false', 'no', 'off'):
                persistent_cache = False
            elif low in ('1', 'true', 'yes', 'on'):
                persistent_cache = True
            else:
                persistent_cache = env
        if persistent_cache:
            from .. import sysconfig

            sysconfig.enable_persistent_compilation_cache(
                persistent_cache if isinstance(persistent_cache, str)
                else None)
        params = inspect.signature(model.forward).parameters
        self._supports_padding = ('positions' in params
                                  and 'kv_start' in params)

    # -- bookkeeping -------------------------------------------------------

    def _sampling_key(self):
        return (self.max_new_tokens, self.temperature, self.top_k,
                self.top_p, self.eos_token_id)

    def _geometry(self, batch, max_len):
        """Batch-capacity component of the registry key: a contiguous
        cache of (batch, max_len). Keeps this engine's keys disjoint
        from ServingEngine's ('paged', ...) keys over the same model."""
        return ('contiguous', int(batch), int(max_len))

    def stats(self):
        """{'trace_counts', 'total_traces', 'cache_keys', 'hits',
        'misses', 'geometry'} — steady-state serving must show
        total_traces frozen across calls (bench.py asserts exactly
        that). `geometry` records the engine kind + capacity knobs that
        feed the registry key, so two engines' stats are attributable."""
        return {
            'trace_counts': trace_counts(),
            'total_traces': total_traces(),
            'cache_keys': len(COMPILE_CACHE),
            'hits': COMPILE_CACHE.hits,
            'misses': COMPILE_CACHE.misses,
            'geometry': {'kind': 'contiguous',
                         'max_new_tokens': self.max_new_tokens,
                         'buckets': self.buckets},
        }

    # -- AOT artifact hooks (paddle_tpu.aot) -------------------------------

    def aot_config(self):
        """Compilation-relevant config as a dict of primitives: what
        two engines must share for one EngineArtifact to serve both.
        Model weight VALUES are deliberately absent (a finetuned
        checkpoint of the same architecture attaches to the same
        artifact) but the STRUCTURE rides in as `model_struct` —
        compilation keys on shapes/dtypes, so a differently-sized model
        of the same class must refuse, not silently miss every cache
        entry."""
        return {
            'engine': 'DecodeEngine',
            'model': model_tag(self.model),
            'model_struct': model_struct(self.model),
            'cache_dtype': str(self.model.cache_dtype()),
            'max_new_tokens': self.max_new_tokens,
            'temperature': self.temperature,
            'top_k': self.top_k,
            'top_p': self.top_p,
            'eos_token_id': self.eos_token_id,
            'buckets': list(self.buckets),
        }

    def registry_key_generate(self, batch, prompt_len, max_new_tokens=None):
        """The EXACT CompileCache key a `generate(ids)` call with this
        (batch, prompt length, budget) would note — the unit
        GeometrySet enumeration is checked against."""
        mnt = (self.max_new_tokens if max_new_tokens is None
               else int(max_new_tokens))
        max_len = bucket_length(int(prompt_len), self.buckets) + mnt
        return COMPILE_CACHE.key(
            self.model, (int(batch), max_len), self.model.cache_dtype(),
            self._sampling_key() + ('generate',),
            geometry=self._geometry(batch, max_len))

    def registry_key_speculative(self, batch, prompt_len, max_new_tokens,
                                 num_draft_tokens):
        """The key a `generate_speculative` call would note (prompts are
        NOT bucketed on that path, so the exact prompt length is part
        of the cache shape)."""
        max_len = int(prompt_len) + int(max_new_tokens) + (
            int(num_draft_tokens) + 1)
        return COMPILE_CACHE.key(
            self.model, (int(batch), max_len), self.model.cache_dtype(),
            (int(num_draft_tokens), 'speculative'),
            geometry=self._geometry(batch, max_len))

    def _aot_jitted_fns(self):
        """The module-level jitted steps this engine's geometries
        dispatch — what `aot.build` cache-evicts (per FUNCTION, not
        process-wide) to force real persisting compiles."""
        return (_prefill_exact, _prefill_padded, _decode_loop,
                _spec_decode_b1, _spec_window_batched)

    def _warm_geometry(self, g, draft=None):
        """Drive ONE enumerated geometry through the LIVE serving path
        (a dummy generate call), populating jax's module-level trace
        cache and the CompileCache registry with exactly the entries a
        real request of this shape will hit. Dummy token ids are zeros;
        outputs are discarded."""
        p = g.params
        ids = jnp.zeros((p['batch'], p['prompt_len']), jnp.int32)
        if g.kind == 'decode_spec':
            if draft is None:
                raise ValueError(
                    'geometry kind decode_spec needs the draft model: '
                    'pass warmup(..., draft=draft_model)')
            self.generate_speculative(
                draft, ids, max_new_tokens=p['max_new_tokens'],
                num_draft_tokens=p['num_draft_tokens'])
        else:
            self.generate(ids, max_new_tokens=p['max_new_tokens'])

    def warmup(self, artifact=None, geometries=None, draft=None):
        """Pre-populate the module-level jit caches (and the
        CompileCache registry) for every geometry this engine will
        dispatch, BEFORE the first request. With `artifact` (an
        `aot.EngineArtifact` or its path) the manifest is
        fingerprint-checked and jax's persistent executable cache is
        wired to the artifact's, so the warmup compiles are disk reads,
        not XLA runs — the zero-compile cold start. Returns a report
        dict; see docs/aot_warmup.md."""
        from ..aot.artifact import warm_attach

        return warm_attach(self, artifact=artifact, geometries=geometries,
                           draft=draft)

    def _export_specs(self, g, draft=None):
        """(suffix, jitted_fn, args) tuples for `aot.build(...,
        export_stablehlo=True)`: the geometry's traced computations
        over ShapeDtypeStruct avals (nothing allocated, nothing
        executed). The model is CLOSED OVER — the jit.save idiom:
        weights ride as constants, so the exported module is
        self-contained and its pytree carries only arrays and
        registered containers (a Layer in the calling convention would
        refuse to serialize). A bucketed generate spans two jitted
        steps, so one geometry exports two StableHLO modules."""
        p = g.params
        if g.kind != 'decode':
            raise NotImplementedError(
                f'no StableHLO export for geometry kind {g.kind!r}')
        B, L = int(p['batch']), int(p['prompt_len'])
        mnt = int(p['max_new_tokens'])
        Sb = bucket_length(L, self.buckets)
        max_len = Sb + mnt
        caches = jax.eval_shape(
            functools.partial(self.model.init_cache, B, max_len))
        ids = jax.ShapeDtypeStruct((B, Sb), jnp.int32)
        rl = jax.ShapeDtypeStruct((B,), jnp.int32)
        exact = L == Sb
        base_pre = (_prefill_exact if exact else _prefill_padded)
        pre_args = (caches, ids) if exact else (caches, ids, rl)
        # tracelint: disable=TL001 - one-shot export wrappers (statics
        # and the model baked into the closure; never a hot path)
        pre = jax.jit(functools.partial(
            getattr(base_pre, '__wrapped__', base_pre), self.model))
        logits_sds, caches_sds = jax.eval_shape(pre, *pre_args)
        yield ('-prefill', pre, pre_args)
        # tracelint: disable=TL001 - one-shot export wrapper (see above)
        dec = jax.jit(functools.partial(
            getattr(_decode_loop, '__wrapped__', _decode_loop),
            self.model, max_new_tokens=mnt, temperature=self.temperature,
            top_k=self.top_k, top_p=self.top_p,
            eos_token_id=self.eos_token_id, padded=not exact))
        yield ('-decode', dec,
               (caches_sds, logits_sds, rl, jax.random.PRNGKey(0)))

    def _cost_specs(self, g, draft=None):
        """(jitted_fn, args, static_kwargs) triples for
        `observability.costs.geometry_cost`: the module-level jitted
        prefill + decode steps a `generate` of this geometry
        dispatches, over ShapeDtypeStruct avals with the live model as
        an argument (the served HLO, not an export variant).
        Speculative geometries have no cost specs (NotImplementedError
        — recorded, never fatal, by the callers)."""
        p = g.params
        if g.kind != 'decode':
            raise NotImplementedError(
                f'no cost specs for geometry kind {g.kind!r}')
        B, L = int(p['batch']), int(p['prompt_len'])
        mnt = int(p['max_new_tokens'])
        Sb = bucket_length(L, self.buckets)
        max_len = Sb + mnt
        caches = jax.eval_shape(
            functools.partial(self.model.init_cache, B, max_len))
        ids = jax.ShapeDtypeStruct((B, Sb), jnp.int32)
        rl = jax.ShapeDtypeStruct((B,), jnp.int32)
        exact = L == Sb
        pre = _prefill_exact if exact else _prefill_padded
        pre_args = ((self.model, caches, ids) if exact
                    else (self.model, caches, ids, rl))
        logits_sds, caches_sds = jax.eval_shape(pre, *pre_args)
        yield (pre, pre_args, {})
        yield (_decode_loop,
               (self.model, caches_sds, logits_sds, rl,
                jax.random.PRNGKey(0)),
               dict(max_new_tokens=mnt, temperature=self.temperature,
                    top_k=self.top_k, top_p=self.top_p,
                    eos_token_id=self.eos_token_id, padded=not exact))

    # -- generate ----------------------------------------------------------

    def generate(self, input_ids, max_new_tokens=None, rng_key=None):
        """Greedy/sampled decode, compiled end to end. Returns
        (B, S + max_new_tokens) ids (the ORIGINAL prompt, not the
        padded one, is echoed back)."""
        input_ids = jnp.asarray(input_ids)
        B, S = input_ids.shape
        mnt = (self.max_new_tokens if max_new_tokens is None
               else int(max_new_tokens))
        Sb = bucket_length(S, self.buckets)
        pad = Sb - S
        if pad and not self._supports_padding:
            raise NotImplementedError(
                f'{type(self.model).__name__} lacks positions/kv_start in '
                f'its cached forward, so bucketed prefill cannot mask the '
                f'pad rows; pass prompts of exactly a bucket length '
                f'{self.buckets} or use a Llama-family model')
        max_len = Sb + mnt
        caches = self.model.init_cache(B, max_len)
        key = self._sampling_key() + ('generate',)
        COMPILE_CACHE.note(COMPILE_CACHE.key(
            self.model, (B, max_len), self.model.cache_dtype(), key,
            geometry=self._geometry(B, max_len)))
        if rng_key is None:
            rng_key = jax.random.PRNGKey(0)
        real_len = jnp.full((B,), S, jnp.int32)
        if pad:
            ids = jnp.pad(input_ids, ((0, 0), (pad, 0)))
            last_logits, caches = _prefill_padded(self.model, caches, ids,
                                                  real_len)
        else:
            last_logits, caches = _prefill_exact(self.model, caches,
                                                 input_ids)
        tokens, caches = _decode_loop(
            self.model, caches, last_logits, real_len, rng_key,
            max_new_tokens=mnt, temperature=self.temperature,
            top_k=self.top_k, top_p=self.top_p,
            eos_token_id=self.eos_token_id, padded=bool(pad))
        return jnp.concatenate([input_ids, tokens.astype(input_ids.dtype)],
                               axis=1)

    # -- speculative -------------------------------------------------------

    def generate_speculative(self, draft, input_ids, max_new_tokens=None,
                             num_draft_tokens=4):
        """Greedy speculative decoding through the fused window step:
        LOSSLESS vs `generate` (temperature 0) on the target alone; one
        host sync per CALL at batch 1 (the window loop is a compiled
        lax.while_loop), per window for batched rows. Prompts are NOT
        bucketed on this path
        (the window loop already reuses one compilation for any prompt
        length via traced offsets... for batch 1; batched rows commit
        per-row via kv_write_pos)."""
        input_ids = jnp.asarray(input_ids)
        B, S = input_ids.shape
        mnt = (self.max_new_tokens if max_new_tokens is None
               else int(max_new_tokens))
        k = int(num_draft_tokens)
        if k < 1:
            raise ValueError('num_draft_tokens must be >= 1')
        if B != 1:
            for m_ in (self.model, draft):
                if 'kv_write_pos' not in inspect.signature(
                        m_.forward).parameters:
                    raise NotImplementedError(
                        f'{type(m_).__name__} does not support batched '
                        f'speculative decoding (cached forward lacks '
                        f'kv_write_pos); loop prompts individually')
        max_len = S + mnt + k + 1
        tcaches = self.model.init_cache(B, max_len)
        dcaches = draft.init_cache(B, max_len)
        COMPILE_CACHE.note(COMPILE_CACHE.key(
            self.model, (B, max_len), self.model.cache_dtype(),
            (k, 'speculative'), geometry=self._geometry(B, max_len)))
        if B == 1:
            gen = _spec_loop_host_b1(self.model, draft, tcaches, dcaches,
                                     input_ids, mnt, k, self.eos_token_id)
        else:
            gen = _spec_loop_host_batched(self.model, draft, tcaches,
                                          dcaches, input_ids, mnt, k,
                                          self.eos_token_id)
        return jnp.concatenate(
            [input_ids, jnp.asarray(gen, input_ids.dtype)], axis=1)


# ---------------------------------------------------------------------------
# Host-side speculative drivers (shared with models/generation.py)
# ---------------------------------------------------------------------------

def _spec_loop_host_b1(target, draft, tcaches, dcaches, input_ids,
                       max_new_tokens, k, eos_token_id):
    """Batch-1 driver: two async prefill dispatches, then the WHOLE
    window loop as one compiled dispatch (_spec_decode_b1) and one
    device_get — a single host sync for the entire generate call."""
    B, S = input_ids.shape
    last_logits, tcaches = _prefill_exact(target, tcaches, input_ids)
    _, dcaches = _prefill_exact(draft, dcaches, input_ids)
    c = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)[:, None]
    buf, n, _, _ = _spec_decode_b1(
        target, draft, tcaches, dcaches, c, jnp.asarray(S, jnp.int32),
        k=k, max_new_tokens=max_new_tokens, eos_token_id=eos_token_id)
    buf, n = jax.device_get((buf, n))       # the ONE host sync
    out = [int(x) for x in buf[:min(int(n), max_new_tokens)]]
    if eos_token_id is not None:
        if eos_token_id in out:
            out = out[:out.index(eos_token_id) + 1]
        out += [eos_token_id] * (max_new_tokens - len(out))
    return [out[:max_new_tokens]]


def _spec_loop_host_batched(target, draft, tcaches, dcaches, input_ids,
                            max_new_tokens, k, eos_token_id):
    """B > 1: rows commit at their own rates (per-row kv_write_pos);
    rule per row identical to batch-1, so losslessness holds row-wise.
    Finished/full rows still ride through the static-shape window but
    commit nothing (their L stays put; scratch rows get overwritten)."""
    B, S = input_ids.shape
    c0, tcaches = _prefill_exact(target, tcaches, input_ids)
    _, dcaches = _prefill_exact(draft, dcaches, input_ids)
    c_host = np.asarray(jnp.argmax(c0, axis=-1)).astype(np.int64)  # (B,)

    out = [[] for _ in range(B)]
    finished = [False] * B
    L = np.full((B,), S, np.int64)

    def row_needs(b):
        return not finished[b] and len(out[b]) < max_new_tokens

    while any(row_needs(b) for b in range(B)):
        cj = jnp.asarray(c_host[:, None], jnp.int32)
        wp = jnp.asarray(L, jnp.int32)
        drafts, choices, m, next_c, tcaches, dcaches = _spec_window_batched(
            target, draft, tcaches, dcaches, cj, wp, k=k)
        # batched rows commit at their OWN rates, so the host must read
        # the per-row accepts between windows — one batched device_get
        # per WINDOW (never per token) is the contract this loop keeps.
        # tracelint: disable=TL002 - single sync per window by design
        d, m_h, nc = jax.device_get((drafts, m, next_c))
        for b in range(B):
            if not row_needs(b):
                continue
            mb = int(m_h[b])
            committed = [int(c_host[b])] + [int(x) for x in d[b, :mb]]
            c_host[b] = int(nc[b])
            out[b].extend(committed)
            if eos_token_id is not None and eos_token_id in committed:
                out[b] = out[b][:out[b].index(eos_token_id) + 1]
                finished[b] = True
            L[b] += len(committed)

    pad = eos_token_id if eos_token_id is not None else 0
    return [out[b][:max_new_tokens]
            + [pad] * (max_new_tokens - len(out[b][:max_new_tokens]))
            for b in range(B)]


def donation_supported():
    """Whether this backend honors jit buffer donation (all current
    CPU/TPU jaxlibs do; the probe keeps tests honest on exotic ones)."""
    x = jnp.zeros((8,))
    # tracelint: disable=TL001 - one-off capability probe, not a hot path
    jax.jit(lambda a: a + 1, donate_argnums=(0,))(x)
    return x.is_deleted()


__all__ = [
    'DecodeEngine', 'CompileCache', 'COMPILE_CACHE', 'DEFAULT_BUCKETS',
    'bucket_length', 'trace_counts', 'total_traces', 'reset_trace_counts',
    'donation_supported', 'model_tag', 'model_struct', 'key_str',
    'key_from_str',
]
