"""The replica fleet — load-aware routing and ms-scale elasticity over
N serving replicas (ROADMAP item 1, docs/serving.md#fleet).

Every per-replica piece already exists: windowed rate gauges and a
drain-aware `/healthz` (PR 14), one shared AOT artifact with
zero-compile `warm_attach` (PR 7), bit-equal `snapshot()`/`restore()`
migration (PR 8/16/18), crash postmortem bundles (PR 12), and
phase-role placement (PR 16). This module is the composition: a
`Fleet` fronts N engine-like replicas (plain `ServingEngine`s,
tp-sharded ones, or `DisaggPair`s) behind ONE submission surface, and
a `Router` — pure policy, no engine references — decides placement
per request off live `ReplicaSignals`:

  - load        queue depth + in-flight (least-loaded first),
  - pressure    watermark-relative pool pressure,
  - health      drain state and the watchdog verdict (a healthz-503 or
                an active SLO breach stops routing there NOW),
  - phase role  bare prefill/decode engines never take fresh
                submissions (a `DisaggPair` routes internally),
  - rates       the PR-14 windowed `serve.tok_s` / `serve.err_rate`
                gauges, scraped in-process from each replica's PRIVATE
                registry (`ReplicaSignals.from_engine`) or over HTTP
                from its `/metrics`+`/healthz` endpoint
                (`ReplicaSignals.from_http`, the cross-process path).

Elasticity is the headline. `scale_to(n)` grows the fleet by building
replicas from the factory and warm-attaching each to ONE shared AOT
artifact — zero compiles after the first replica warms, so capacity
follows traffic at millisecond scale. Scale-down drains the victim,
snapshots it, and scatters its requests across the survivors via
`ServingEngine.adopt_request` (the restore contract per request:
re-prefill resumes every stream bit-equal). `restart(name)` is the
rolling-restart recipe fleet-level: spin the replacement FIRST, then
migrate, then close. A replica whose `step()` raises (the PR-8 worker
death, or the `replica_step` fault seam) is resurrected: its
auto-dumped postmortem bundle is read back and its snapshot restored
onto a fresh zero-compile standby — requests ride through the crash.

The autoscaling clock: replicas in one process share one core, so
wall-clock fleet throughput cannot exceed one replica's. The fleet
therefore keeps a SIMULATED clock — each `step()` round steps every
replica once and advances `sim_time_s` by the MAX per-replica wall
time, i.e. replicas are parallel hosts of the simulated deployment
(exactly how a dp fleet behaves on real hardware). Sim-time feeds the
TTFT percentiles and the scale-throughput ratio `gate_fleet_sim`
asserts; every real-execution property (bit-equal streams, zero
retraces, zero leaked pages) stays measured on real execution.

Telemetry rides the existing stack, fleet-scoped into the PROCESS
registry/journal (each replica's serve.*/pool.* series live in its
private registry): counters `fleet.routed`, `fleet.migrations`,
`fleet.resurrections`, `fleet.restarts`, per-replica
`fleet.routed.<name>`; gauges `fleet.replicas`,
`fleet.route_share.<name>`; histogram `fleet.ttft_sim_ms`; journal
events `fleet_scale` / `fleet_migrate` / `fleet_resurrect` /
`fleet_restart`.
"""
from __future__ import annotations

import time

from ..observability import journal as _journal
from ..observability import metrics as _obs
from ..testing import faults as _faults
from .serving import QueueFull

__all__ = ['ReplicaSignals', 'Router', 'Fleet', 'NoEligibleReplica',
           'FLEET_SNAPSHOT_SCHEMA']

# the fleet_snapshot wire format version (statelint wire claim):
# {'schema', 'replicas': {name: {'index', 'snapshot'}}, 'where',
#  'counts', 'sim_time_s', 'next_index'}
FLEET_SNAPSHOT_SCHEMA = 1

# roles a fresh submission may route to: a bare prefill/decode engine
# is half of a pair — its pool either never decodes or never admits,
# so placing new work there strands it
_SUBMITTABLE_ROLES = ('monolithic', 'pair')


class NoEligibleReplica(RuntimeError):
    """Every replica is draining, breaching, full, or role-excluded."""


def _pair_role(engine):
    return ('pair' if hasattr(engine, 'prefill')
            and hasattr(engine, 'decode') else 'monolithic')


class ReplicaSignals:
    """One replica's routing inputs as a pure value — the Router never
    touches an engine, so policy is unit-testable from synthetic
    signals alone (and the same decision runs off an HTTP scrape)."""

    __slots__ = ('name', 'role', 'healthy', 'draining', 'breaching',
                 'queue_depth', 'in_flight', 'pool_pressure', 'tok_s',
                 'err_rate')

    def __init__(self, name, *, role='monolithic', healthy=True,
                 draining=False, breaching=False, queue_depth=0,
                 in_flight=0, pool_pressure=0.0, tok_s=None,
                 err_rate=0.0):
        self.name = str(name)
        self.role = role
        self.healthy = bool(healthy)
        self.draining = bool(draining)
        self.breaching = bool(breaching)
        self.queue_depth = int(queue_depth)
        self.in_flight = int(in_flight)
        self.pool_pressure = float(pool_pressure)
        self.tok_s = tok_s
        self.err_rate = float(err_rate)

    @property
    def load(self):
        """Outstanding work: what least-loaded routing minimizes."""
        return self.queue_depth + self.in_flight

    def __repr__(self):
        return (f'ReplicaSignals({self.name!r}, role={self.role!r}, '
                f'healthy={self.healthy}, draining={self.draining}, '
                f'breaching={self.breaching}, load={self.load}, '
                f'pressure={self.pool_pressure:.2f}, '
                f'tok_s={self.tok_s}, err_rate={self.err_rate:.3f})')

    # -- scraping ----------------------------------------------------------

    @classmethod
    def from_engine(cls, name, engine):
        """In-process scrape: host truth (queue/slots/allocator) plus
        the replica's PRIVATE registry's windowed rate gauges and its
        watchdog verdict — the same numbers `/metrics` and `/healthz`
        would serve, without the HTTP round trip. Works for a
        `DisaggPair` too (signals aggregate across both pools)."""
        role = getattr(engine, 'phase_role', None) or _pair_role(engine)
        if role == 'pair':
            prefill, decode = engine.prefill, engine.decode
            qd = (len(prefill.queue) + len(decode.queue)
                  + len(engine._pending))
            pressure = max(
                prefill.allocator.utilization() / prefill.admit_watermark,
                decode.allocator.utilization() / decode.admit_watermark)
            draining = prefill.draining or decode.draining
            parts = (prefill, decode)
        else:
            qd = len(engine.queue)
            a = engine.allocator
            pressure = a.utilization() / engine.admit_watermark
            draining = engine.draining
            parts = (engine,)
        breaching, healthy = False, True
        tok_s, err_rate = None, 0.0
        for part in parts:
            wd = getattr(part, '_watchdog', None)
            if wd is not None and not wd.verdict()['healthy']:
                breaching, healthy = True, False
            reg = getattr(part, '_registry', None)
            if reg is not None:
                g = reg.get('serve.tok_s')
                if g is not None:
                    tok_s = (tok_s or 0.0) + g.value
                g = reg.get('serve.err_rate')
                if g is not None:
                    err_rate = max(err_rate, g.value)
        return cls(name, role=role, healthy=healthy and not draining,
                   draining=draining, breaching=breaching,
                   queue_depth=qd, in_flight=engine.in_flight(),
                   pool_pressure=pressure, tok_s=tok_s,
                   err_rate=err_rate)

    @classmethod
    def from_http(cls, name, base_url, timeout=2.0):
        """Cross-process scrape off a replica's ops endpoint: verdict
        from `/healthz` (a 503 — breach OR draining — is ineligible),
        gauges from `/metrics` Prometheus text. Any transport error
        reads as unhealthy: a replica that cannot answer its own
        health check must not take traffic."""
        import json as _json
        import urllib.error
        import urllib.request

        base = base_url.rstrip('/')
        try:
            with urllib.request.urlopen(base + '/healthz',
                                        timeout=timeout) as r:
                hz, code = _json.loads(r.read()), r.status
        except urllib.error.HTTPError as e:       # 503 carries a body
            hz, code = _json.loads(e.read()), e.code
        except Exception:  # noqa: BLE001 - unreachable = unhealthy
            return cls(name, healthy=False, breaching=True)
        try:
            with urllib.request.urlopen(base + '/metrics',
                                        timeout=timeout) as r:
                gauges = _parse_prometheus(r.read().decode())
        except Exception:  # noqa: BLE001
            gauges = {}
        draining = hz.get('status') == 'draining'
        return cls(
            name, role=hz.get('phase_role', 'monolithic'),
            healthy=code == 200, draining=draining,
            breaching=code != 200 and not draining,
            queue_depth=int(gauges.get('serve_queue_depth', 0)),
            in_flight=int(gauges.get('serve_in_flight', 0)),
            pool_pressure=gauges.get('serve_pool_pressure', 0.0),
            tok_s=gauges.get('serve_tok_s'),
            err_rate=gauges.get('serve_err_rate', 0.0))


def _parse_prometheus(text):
    """name -> value for the plain (label-free) samples in a
    Prometheus 0.0.4 text page — the gauges the router reads are all
    label-free, so histogram series with `{le=...}` labels are simply
    skipped."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith('#') or '{' in line:
            continue
        parts = line.split()
        if len(parts) != 2:
            continue
        try:
            out[parts[0]] = float(parts[1])
        except ValueError:
            continue
    return out


class Router:
    """Pure placement policy over `ReplicaSignals` — deterministic,
    engine-free, unit-testable.

    `eligible()` drops replicas that must not take fresh work:
    draining, unhealthy/breaching, role-excluded (bare prefill/decode
    halves), or at/over pool pressure `max_pressure`. `choose()` ranks
    the eligible set least-loaded first, then lowest pool pressure,
    then lowest windowed error rate, then HIGHEST windowed tok/s, and
    finally by name — the total order that makes every tie-break
    deterministic (gate parity depends on reproducible placement)."""

    def __init__(self, max_pressure=None):
        self.max_pressure = (None if max_pressure is None
                             else float(max_pressure))

    def eligible(self, signals):
        out = []
        for s in signals:
            if s.draining or s.breaching or not s.healthy:
                continue
            if s.role not in _SUBMITTABLE_ROLES:
                continue
            if (self.max_pressure is not None
                    and s.pool_pressure >= self.max_pressure):
                continue
            out.append(s)
        return out

    @staticmethod
    def _rank(s):
        return (s.load, s.pool_pressure, s.err_rate,
                -(s.tok_s if s.tok_s is not None else 0.0), s.name)

    def choose(self, signals):
        """The eligible replicas, best placement first (empty when
        nothing is eligible — the caller decides whether that is
        backpressure or an outage)."""
        return sorted(self.eligible(signals), key=self._rank)


class Fleet:
    """N replicas behind one submission surface.

    `factory(**kw)` builds ONE replica; the fleet calls it with
    `metrics_registry=` (a fresh private `MetricsRegistry` — the
    per-replica series isolation the router's signals need),
    `rid_start=` (disjoint `rid_stride`-sized id spaces, so a request
    keeps its rid across migration/resurrection hops), and
    `postmortem_dir=` (where a killed replica's bundle lands). Pass
    `artifact=` (a PR-7 AOT artifact dir) and every replica after the
    first warms zero-compile; pre-built engines/pairs join via
    `add()` and manage their own warmth.

    The fleet steps its replicas round-robin per `step()` call and
    advances the simulated deployment clock `sim_time_s` by the max
    per-replica wall per round (see the module docstring). All
    fleet-level counters/gauges land in the PROCESS registry."""

    def __init__(self, factory=None, *, router=None, artifact=None,
                 rid_stride=1 << 20, postmortem_dir=None,
                 name_prefix='replica'):
        self.factory = factory
        self.router = router if router is not None else Router()
        self.artifact = artifact
        self.rid_stride = int(rid_stride)
        if self.rid_stride < 1:
            raise ValueError('rid_stride must be >= 1')
        self.postmortem_dir = postmortem_dir
        self.name_prefix = str(name_prefix)
        self.replicas: dict = {}      # name -> engine-like, step order
        self._index: dict = {}        # name -> rid-stride index
        self._next_index = 0
        self._where: dict = {}        # rid -> replica name
        self._round = 0
        self.sim_time_s = 0.0
        # sim-time TTFT bookkeeping: rid -> submit sim-time while the
        # first token is pending, then rid -> sim TTFT seconds
        # (bounded — oldest evicted — so a long flood can't grow it)
        self._submit_t: dict = {}
        self._ttft: dict = {}
        self.max_ttft_records = 4096
        self.counts = {'routed': 0, 'migrations': 0, 'resurrections': 0,
                       'restarts': 0}
        self._routed_by: dict = {}    # name -> requests routed there

    # -- replica lifecycle -------------------------------------------------

    def _require_factory(self):
        if self.factory is None:
            raise RuntimeError(
                'this Fleet has no factory — scale_to()/restart()/'
                'resurrection need one to build replicas (pass '
                'factory=, or add() pre-built replicas only)')

    def _new_replica(self):
        """Build + warm one replica from the factory on a fresh
        private registry and a disjoint rid stride. With a shared
        artifact the warm is `warm_attach` — zero compiles after the
        first replica in the process warmed (the ms-scale elasticity
        contract gate_fleet_sim pins)."""
        self._require_factory()
        idx = self._next_index
        self._next_index += 1
        name = f'{self.name_prefix}{idx}'
        eng = self.factory(metrics_registry=_obs.MetricsRegistry(),
                           rid_start=idx * self.rid_stride,
                           postmortem_dir=self.postmortem_dir)
        if self.artifact is not None:
            eng.warmup(artifact=self.artifact)
        self.replicas[name] = eng
        self._index[name] = idx
        self._set_replica_gauges()
        return name

    def add(self, name, engine, index=None):
        """Adopt a pre-built replica (a tp-sharded engine, a
        `DisaggPair`, anything engine-like). `index` reserves a rid
        stride for bookkeeping symmetry; the caller owns the engine's
        actual `rid_start` (and its warmth)."""
        name = str(name)
        if name in self.replicas:
            raise ValueError(f'replica {name!r} already exists')
        if index is None:
            index = self._next_index
        self._next_index = max(self._next_index, int(index) + 1)
        self.replicas[name] = engine
        self._index[name] = int(index)
        self._set_replica_gauges()
        return name

    def scale_to(self, n):
        """Grow or shrink to `n` replicas. Growth builds+warms from
        the factory (zero-compile under a shared artifact); shrink
        drains the youngest replicas and migrates their requests to
        the survivors before closing them. Returns the replica-name
        list after scaling."""
        n = int(n)
        if n < 1:
            raise ValueError('a fleet keeps at least one replica')
        before = len(self.replicas)
        while len(self.replicas) < n:
            self._new_replica()
        while len(self.replicas) > n:
            victim = next(reversed(self.replicas))
            self._retire_replica(victim)
        if len(self.replicas) != before:
            _journal.record('fleet_scale', n_from=before,
                            n_to=len(self.replicas))
        return list(self.replicas)

    def _retire_replica(self, name):
        """Drain `name`, migrate everything it holds to survivors,
        close it, and forget it."""
        eng = self.replicas[name]
        eng.drain(True)
        self._migrate(name)
        eng.close()
        del self.replicas[name]
        del self._index[name]
        self._set_replica_gauges()

    def _migrate(self, victim):
        """Scatter every request the draining victim holds across the
        surviving replicas via `adopt_request` — per request the
        restore contract, so each migrated stream finishes bit-equal
        to an uninterrupted run. Terminal-but-unretrieved records move
        too: `result(rid)` answers on the survivor."""
        eng = self.replicas[victim]
        snap = eng.snapshot()
        trails = snap.get('trails') or {}
        moved = 0
        for rec in list(snap['requests']) + list(snap['terminal']):
            rid = int(rec['rid'])
            if self._where.get(rid, victim) != victim:
                continue               # already adopted elsewhere
            target = self._pick_survivor(exclude=victim)
            self.replicas[target].adopt_request(
                rec, trail=trails.get(str(rid)))
            if rid in self._where:
                self._where[rid] = target
            moved += 1
        if moved:
            self.counts['migrations'] += moved
            _obs.inc('fleet.migrations', moved)
        _journal.record('fleet_migrate', replica=victim, moved=moved)
        return moved

    def _pick_survivor(self, exclude):
        # migration needs adopt_request on the target — a DisaggPair
        # can serve fresh traffic but not splice a foreign record in
        sigs = [s for s in self.signals()
                if s.name != exclude
                and hasattr(self.replicas[s.name], 'adopt_request')]
        ranked = self.router.choose(sigs)
        if not ranked:
            raise NoEligibleReplica(
                f'cannot migrate off {exclude!r}: no eligible surviving '
                f'replica (scale up first, or undrain a survivor)')
        return ranked[0].name

    def restart(self, name):
        """Rolling restart of one replica: spin the replacement FIRST
        (zero-compile warm under the shared artifact), then drain +
        migrate + close the old one — fleet capacity never dips below
        N. Returns the replacement's name."""
        if name not in self.replicas:
            raise KeyError(f'unknown replica {name!r}')
        self._require_factory()
        fresh = self._new_replica()
        self._retire_replica(name)
        self.counts['restarts'] += 1
        _obs.inc('fleet.restarts')
        _journal.record('fleet_restart', replica=name, replacement=fresh)
        return fresh

    def _resurrect(self, name, error):
        """A replica's step() raised — the worker-death path. Ensure
        its postmortem bundle exists (step() already auto-dumped on a
        real crash; the fault-seam path dumps here), read the bundle's
        snapshot back, and restore it onto a fresh zero-compile
        standby. The dead replica's requests — queued, preempted, AND
        the running ones, re-entering as preempted — ride through the
        crash; only the resurrection is observable (a `fleet_resurrect`
        event and the counter)."""
        from ..observability import postmortem as _postmortem

        eng = self.replicas.pop(name)
        self._index.pop(name, None)
        if getattr(eng, 'last_postmortem', None) is None:
            eng._auto_postmortem(error)
        bundle_path = getattr(eng, 'last_postmortem', None)
        if bundle_path is None:
            raise RuntimeError(
                f'replica {name!r} died ({error!r}) without a '
                f'postmortem bundle — give the fleet (or the replica) '
                f'a postmortem_dir so its requests can resurrect'
            ) from error
        snap = _postmortem.load_bundle(bundle_path)['snapshot']
        standby = self._new_replica()
        self.replicas[standby].restore(snap)
        for rid, owner in list(self._where.items()):
            if owner == name:
                self._where[rid] = standby
        try:
            eng.close()
        except Exception:  # noqa: BLE001 - it already crashed
            pass
        self.counts['resurrections'] += 1
        _obs.inc('fleet.resurrections')
        _journal.record('fleet_resurrect', replica=name,
                        standby=standby, error=repr(error),
                        bundle=bundle_path)
        self._set_replica_gauges()
        return standby

    # -- the serving surface -----------------------------------------------

    def signals(self):
        """Live `ReplicaSignals` for every replica, in step order."""
        return [ReplicaSignals.from_engine(name, eng)
                for name, eng in self.replicas.items()]

    def submit(self, prompt, **kw):
        """Route one request: rank the eligible replicas and place on
        the best one that accepts (a QueueFull there falls through to
        the next — shedding is a per-replica verdict, the fleet's job
        is to find room). Raises `NoEligibleReplica` when no replica
        may take fresh work."""
        ranked = self.router.choose(self.signals())
        if not ranked:
            raise NoEligibleReplica(
                'no replica is eligible for new work (all draining, '
                'breaching, or role-excluded)')
        last_full = None
        for s in ranked:
            try:
                rid = self.replicas[s.name].submit(prompt, **kw)
            except QueueFull as e:
                last_full = e
                continue
            self._where[rid] = s.name
            self._submit_t[rid] = self.sim_time_s
            self.counts['routed'] += 1
            self._routed_by[s.name] = self._routed_by.get(s.name, 0) + 1
            _obs.inc('fleet.routed')
            _obs.inc(f'fleet.routed.{s.name}')
            self._set_share_gauges()
            return rid
        raise last_full

    def step(self):
        """One fleet round: step every replica once (the `replica_step`
        fault seam fires per replica first — a scripted kill looks
        exactly like that replica's step() raising), resurrect any
        replica that died, advance the sim clock by the round's max
        per-replica wall, and settle sim-time TTFTs. Returns the
        round's finished Requests across all replicas."""
        finished = []
        max_wall = 0.0
        for name in list(self.replicas):
            eng = self.replicas.get(name)
            if eng is None:
                continue
            t0 = time.perf_counter()
            try:
                if _faults.ACTIVE is not None:
                    _faults.fire('replica_step', replica=name,
                                 step=self._round)
                finished.extend(eng.step())
            except Exception as e:  # noqa: BLE001 - any step() escape
                #   is a worker death; the fleet's job is to resurrect
                self._resurrect(name, e)
                continue
            max_wall = max(max_wall, time.perf_counter() - t0)
        self._round += 1
        self.sim_time_s += max_wall
        self._settle_ttft()
        return finished

    def _settle_ttft(self):
        """Move rids whose first token landed this round from the
        pending map to the TTFT record (sim-time milliseconds, into
        the `fleet.ttft_sim_ms` histogram and the bounded dict the
        percentile report reads)."""
        if not self._submit_t:
            return
        for rid in list(self._submit_t):
            name = self._where.get(rid)
            eng = self.replicas.get(name) if name is not None else None
            if eng is None:
                self._submit_t.pop(rid)
                continue
            req = self._req_of(eng, rid)
            if req is None:            # terminal before we looked:
                #   count submit->now (an upper bound, never an
                #   undercount) so failed-fast requests don't vanish
                ttft = self.sim_time_s - self._submit_t.pop(rid)
            elif req.generated:
                ttft = self.sim_time_s - self._submit_t.pop(rid)
            else:
                continue
            self._ttft[rid] = ttft
            _obs.observe('fleet.ttft_sim_ms', ttft * 1e3)
            while len(self._ttft) > self.max_ttft_records:
                self._ttft.pop(next(iter(self._ttft)))

    @staticmethod
    def _req_of(engine, rid):
        if hasattr(engine, '_live'):
            r = engine._live.get(rid)
            if r is None:
                r = engine._terminal.get(rid)
            return r
        # DisaggPair: the request lives in exactly one pool
        return (Fleet._req_of(engine.prefill, rid)
                or Fleet._req_of(engine.decode, rid))

    def result(self, rid):
        """Terminal outcome of `rid`, wherever it lives now (routing,
        migration, and resurrection all keep `_where` current)."""
        name = self._where.get(rid)
        if name is None or name not in self.replicas:
            raise KeyError(f'unknown rid {rid} (never routed here, or '
                           f'already retrieved)')
        out = self.replicas[name].result(rid)
        self._where.pop(rid, None)
        self._submit_t.pop(rid, None)
        return out

    def status(self, rid):
        name = self._where.get(rid)
        if name is None or name not in self.replicas:
            raise KeyError(f'unknown rid {rid}')
        return self.replicas[name].status(rid)

    def drain(self, name, on=True):
        """Flip one replica's drain flag (the router stops/resumes
        routing there on the next signals() read)."""
        self.replicas[name].drain(on)

    def in_flight(self):
        return sum(e.in_flight() for e in self.replicas.values())

    def queue_depth(self):
        return sum(s.queue_depth for s in self.signals())

    def run(self, max_steps=None):
        """Step until every replica is idle (or `max_steps`)."""
        steps = 0
        while any(e.in_flight() or s.queue_depth
                  for e, s in zip(self.replicas.values(),
                                  self.signals())):
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return steps

    # -- observability -----------------------------------------------------

    def _set_replica_gauges(self):
        _obs.set_gauge('fleet.replicas', len(self.replicas))

    def _set_share_gauges(self):
        total = self.counts['routed']
        if not total:
            return
        for name, n in self._routed_by.items():
            _obs.set_gauge(f'fleet.route_share.{name}', n / total)

    def route_shares(self):
        """name -> fraction of all routed requests placed there
        (includes replicas that no longer exist — the shares are a
        lifetime census, like the counters they derive from)."""
        total = self.counts['routed']
        return {name: n / total for name, n in self._routed_by.items()
                } if total else {}

    def ttft_percentiles(self, ps=(50, 95, 99)):
        """Sim-time TTFT percentiles (milliseconds) over the recorded
        requests — nearest-rank over the exact per-rid values, not the
        histogram's bucket interpolation."""
        vals = sorted(self._ttft.values())
        if not vals:
            return {f'p{p}': None for p in ps}
        out = {}
        for p in ps:
            k = min(len(vals) - 1,
                    max(0, int(round(p / 100 * len(vals) + 0.5)) - 1))
            out[f'p{p}'] = vals[k] * 1e3
        return out

    def stats(self):
        return {
            'replicas': {name: eng.stats()
                         for name, eng in self.replicas.items()},
            'sim_time_s': self.sim_time_s,
            'rounds': self._round,
            'counts': dict(self.counts),
            'route_shares': self.route_shares(),
            'ttft_sim_ms': self.ttft_percentiles(),
        }

    # -- fleet snapshot (the fleet_snapshot wire) --------------------------

    def snapshot(self):
        """JSON-able fleet state: every replica's engine snapshot plus
        the fleet's own routing table and clocks — enough for a fresh
        `Fleet` over the same factory to `restore()` and finish every
        stream bit-equal."""
        return {
            'schema': FLEET_SNAPSHOT_SCHEMA,
            'replicas': {name: {'index': self._index[name],
                                'snapshot': eng.snapshot()}
                         for name, eng in self.replicas.items()},
            'where': {str(rid): name
                      for rid, name in self._where.items()},
            'counts': dict(self.counts),
            'sim_time_s': self.sim_time_s,
            'next_index': self._next_index,
        }

    def restore(self, snap):
        """Rebuild a `snapshot()` onto THIS fresh fleet (no replicas
        yet): one factory-built replica per snapshot entry, each
        engine-restored, the routing table and counters carried over."""
        if self.replicas:
            raise RuntimeError('restore() needs a fresh fleet — this '
                               'one already has replicas')
        if snap.get('schema') != FLEET_SNAPSHOT_SCHEMA:
            raise ValueError(
                f"unsupported fleet_snapshot schema "
                f"{snap.get('schema')!r} (this fleet reads schema "
                f'{FLEET_SNAPSHOT_SCHEMA})')
        self._require_factory()
        for name, ent in snap['replicas'].items():
            idx = int(ent['index'])
            eng = self.factory(
                metrics_registry=_obs.MetricsRegistry(),
                rid_start=idx * self.rid_stride,
                postmortem_dir=self.postmortem_dir)
            if self.artifact is not None:
                eng.warmup(artifact=self.artifact)
            eng.restore(ent['snapshot'])
            self.replicas[name] = eng
            self._index[name] = idx
            self._next_index = max(self._next_index, idx + 1)
        self._where = {int(rid): name
                       for rid, name in snap.get('where', {}).items()}
        for k, v in snap.get('counts', {}).items():
            if k in self.counts:
                self.counts[k] = int(v)
        self.sim_time_s = float(snap.get('sim_time_s', 0.0))
        self._next_index = max(self._next_index,
                               int(snap.get('next_index', 0)))
        self._set_replica_gauges()
        return {'replicas': len(self.replicas),
                'where': len(self._where)}

    def close(self):
        """Close every replica (idempotent)."""
        for name in list(self.replicas):
            try:
                self.replicas[name].close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
            self.replicas.pop(name, None)
            self._index.pop(name, None)
        self._set_replica_gauges()
