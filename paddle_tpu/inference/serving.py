"""ServingEngine — continuous batching over a paged KV-cache block pool.

ref (capability): the reference serving stack's block_multihead_attention
paged caches + its request-level serving loop; design lineage: Orca
iteration-level scheduling over vLLM PagedAttention pages. PR 1's
DecodeEngine made a SINGLE static batch fast (one fused dispatch per
window, donated caches, zero steady-state retraces) but a request that
finishes early holds its padded slot until the whole batch drains and
new requests wait for a full generate() call. This module schedules at
the ITERATION level instead:

  1. `BlockAllocator` owns a pool of fixed-size KV pages shared by all
     in-flight requests (free-list alloc/free, page ids recycled
     LIFO, page 0 reserved as the scratch page inactive rows write to).
     The device pool arrays are allocated ONCE per engine
     (`model.init_paged_cache`) and never resized — allocation is pure
     id bookkeeping, so admitting/retiring a request moves zero cache
     bytes.

  2. `ServingEngine.step()` is one scheduler iteration over a FIXED-SLOT
     in-flight batch (`max_slots` rows, shapes never change):
       - retire/admit: finished rows already freed their pages; queued
         requests prefill into freshly allocated pages through the
         bucketed `_paged_prefill` (one compilation per bucket, the
         PR-1 discipline);
       - decode: ALL slots advance `decode_window` tokens in ONE fused
         jitted dispatch (`_serve_window`: a lax.scan whose single-token
         steps route the model through `cached_attention`'s
         PagedKVCache branch — the pallas paged kernel on TPU, a gather
         reference elsewhere), with ONE host sync per window to read
         the emitted tokens.
     Because slot count, page-pool shape, and window length are static,
     requests joining and leaving the batch never change a traced
     shape: steady-state serving is ZERO retraces (`trace_counts()`,
     shared with inference.engine, proves it; bench.py gates on it).

  3. Preemption: when the pool runs out of pages mid-decode, the
     lowest-priority (then youngest) in-flight request is EVICTED — its
     pages are freed, its prompt + generated prefix goes back to the
     queue — and later resumes by re-prefilling prompt+prefix (greedy
     decoding makes the resumed stream exactly the uninterrupted one).

  4. Prefix caching + chunked prefill (both opt-in, both bit-equal;
     docs/serving.md#prefix-caching-and-chunked-prefill):
     `prefix_cache=True` shares full pages of identical prompt
     prefixes across requests through the allocator's refcounted
     content-hash index (copy-on-write on the one page a
     full-coverage hit must rewrite; refcount-0 pages park on a
     hittable LRU) and prefills only the unshared suffix;
     `prefill_chunk=N` admits long prompts as <=N-token chunks fused
     with the decode window (`_serve_chunk_step`), so one 8k-token
     arrival never stalls in-flight streams for a whole-prompt
     prefill. A chunked request occupies its slot but emits nothing
     until its last chunk commits — then its first window runs in the
     SAME dispatch, preserving monolithic semantics exactly.

  5. Tensor parallelism (docs/serving.md#tp-sharded-serving):
     `ServingEngine(model, tp=4)` (or `mesh=serving_mesh(4)`) runs the
     SAME scheduler loop against TP-sharded device state — page pools
     carry a NamedSharding splitting the kv-head dim over the 'tp'
     axis, the fused dispatches run the llama forward through the
     megatron column->row layout (GSPMD inserts the per-layer
     all-reduces; the `serving/*` shardlint suites gate the census
     against a declared budget), and block tables, slot/context
     mirrors, and ALL host scheduler state stay replicated — greedy
     streams are bit-equal to the single-device engine, zero
     steady-state retraces included.

  6. Speculative + quantized + per-request-sampled serving
     (docs/serving.md#speculative-and-quantized-serving):
     `ServingEngine(model, draft=..., num_draft_tokens=k)` turns every
     non-chunk iteration into a fused draft-propose / target-verify
     window (the DecodeEngine's shared draft contract over the paged
     pool: per-slot accept counts make the step output ragged,
     committed through the per-row kv_write_pos machinery) — greedy
     streams bit-equal to the non-speculative engine, sampled rows
     rejection-sampled distribution-correct. `kv_cache_dtype='int8'`
     backs the slots with int8 paged pools (QuantPagedKVCache:
     per-row scales ride with the pages, so quantization survives
     prefix sharing, CoW, preemption, and restore bit-identically) —
     double the effective KV capacity. Sampling params (temperature,
     top-k/p, per-request seed) are SLOT STATE, uploaded as device
     data: a mixed greedy/sampled/speculative workload shares one
     batch with zero retraces as the mix changes.

Engine-level sampling config provides per-request defaults; greedy
(temperature=0) is the parity-tested path: per-request outputs are
exactly `DecodeEngine.generate`'s batch-1 outputs. See docs/serving.md
for the scheduler loop and the block-table layout.

Resilience (docs/serving.md#resilience): requests carry optional
deadlines and can be cancelled; `submit()` load-sheds against a
bounded queue (`QueueFull`) and a pool-pressure watermark pauses
admission before OutOfBlocks can force a preemption storm; a request
that cannot be served — pool still dry after maximal preemption, or a
fault injected during its prefill — FAILS alone (`state='failed'`,
pages freed) while the rest of the batch keeps decoding; and
`snapshot()`/`restore()` capture the host-authoritative scheduler
state so a supervisor can rebuild a crashed replica (warmed from a
PR-7 AOT artifact) and finish every stream bit-equal to an
uninterrupted run. Failure paths are exercised on purpose through the
`paddle_tpu.testing.faults` seams wired at the host boundaries below.
"""
from __future__ import annotations

import contextlib
import functools
import hashlib
import heapq
import inspect
import itertools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import journal as _journal
from ..observability import metrics as _obs
from ..observability import timeseries as _obs_ts
from ..observability import tracing as _obs_trace
# top-level like the rest of the observability imports: the package
# __init__ already pulls watchdog/httpd eagerly, so deferring these
# would save nothing and only hide the dependency
from ..observability import watchdog as _obs_wd
from ..observability.httpd import start_ops_server as _start_ops_server
from ..testing import faults as _faults
from ._schema import KV_BLOB_KIND, SNAPSHOT_SCHEMA
from .engine import (COMPILE_CACHE, DEFAULT_BUCKETS, _count_trace,
                     bucket_length, total_traces, trace_counts)


class OutOfBlocks(RuntimeError):
    """The block pool cannot satisfy an allocation. The ServingEngine
    catches this and preempts; direct BlockAllocator users see it
    raised deterministically (need/have in the message)."""


class QueueFull(RuntimeError):
    """`submit()` rejected the request: the admission queue is at
    `max_queue` and the shed policy found nothing to displace. The
    deterministic load-shedding signal — callers back off and retry,
    instead of the queue growing without bound until preemption storms
    or host OOM kill every in-flight request."""


class InvalidSamplingParams(ValueError):
    """`submit()` rejected a request's per-request sampling params
    (temperature < 0, top_p outside (0, 1]) BEFORE the prompt copy was
    paid — the typed pre-admission validation signal (top_k is clamped
    to the vocab instead, mirroring `filter_logits`'s HF semantics)."""


class RequestError(RuntimeError):
    """Base for terminal non-success request states, raised by
    `result()`. Carries `rid`, the terminal `state`, a human `reason`,
    and (for failures) the original `error` object."""

    state = 'unknown'

    def __init__(self, rid, reason, error=None):
        super().__init__(f'request {rid} {self.state}: {reason}')
        self.rid = rid
        self.reason = reason
        self.error = error


class RequestFailed(RequestError):
    """The request is unservable (pool can never fit it even drained,
    or a fault hit its prefill/admission). `error` is the underlying
    exception (a repr string after snapshot/restore)."""

    state = 'failed'


class RequestExpired(RequestError):
    """The request's `deadline_s` passed before it finished (checked
    at the per-window commit sync and at admission)."""

    state = 'expired'


class RequestCancelled(RequestError):
    """The request was cancelled (`cancel(rid)`) or shed from a full
    queue by a higher-priority arrival (`reason` says which)."""

    state = 'cancelled'


class BlockAllocator:
    """Free-list allocator over a fixed pool of KV-cache pages, with
    per-page REFCOUNTS and a content-hash PREFIX INDEX (vLLM-style
    prefix caching — ROADMAP item 2).

    Pure id bookkeeping: the device page pools live in the engine and
    are NEVER reallocated — alloc/free hand out integer page ids, so
    the pool stays pointer-stable across any alloc/free sequence. Page
    0 is reserved as the scratch page (inactive/frozen slots write
    there), so usable capacity is num_blocks - 1 and every handed-out
    id is >= 1. Freed ids are reused LIFO (most-recently-freed first —
    deterministic, and the hottest pages stay hot).

    Prefix caching: a page holding a FULL block of prompt-token KV can
    be bound to its chain hash (`register_prefix`); a later request
    whose prompt starts with the same token pages walks the chain
    (`match_prefix`) and takes references on the pages (`share` —
    refcount++ instead of alloc: the KV bytes are reused and the
    prefill compute for those tokens is skipped). A freed page whose
    refcount hits zero parks on an LRU of CACHED pages (still indexed,
    still hittable) instead of the free list; `alloc` harvests the LRU
    oldest-first only once the free list runs dry, so caching never
    shrinks the allocatable pool. `cow` swaps a writer's reference on
    a shared page for a private fresh page (copy-on-write — the device
    row copy is the engine's job; the allocator only moves ids)."""

    def __init__(self, num_blocks, block_size):
        num_blocks = int(num_blocks)
        if num_blocks < 2:
            raise ValueError(
                f'num_blocks must be >= 2 (page 0 is the reserved '
                f'scratch page), got {num_blocks}')
        self.num_blocks = num_blocks
        self.block_size = int(block_size)
        # LIFO stack, low ids on top: the first alloc after init hands
        # out 1, 2, ... in order (deterministic, test-friendly)
        self._free = list(range(num_blocks - 1, 0, -1))
        self._ref: dict = {}             # page -> refcount (held pages)
        # prefix index: chain hash <-> page, plus the refcount-0 cached
        # pages in least-recently-freed-first order (python dicts are
        # insertion-ordered, so "pop oldest" is one iteration step and
        # "re-free" reinserts at the tail)
        self._index: dict = {}           # chain hash -> page
        self._hash_of: dict = {}         # page -> chain hash (indexed)
        self._cached: dict = {}          # page -> None (LRU, oldest first)
        self.alloc_count = 0
        self.free_count = 0
        self.high_water = 0
        self.cow_count = 0               # copy-on-write page swaps
        self.prefix_shares = 0           # pages handed out via share()
        self.prefix_evictions = 0        # cached pages harvested by alloc
        # device bytes one page costs across ALL layers (k + v), set by
        # the owning engine from the real pool arrays (the allocator
        # itself only moves ids); stats() reports real-unit pool sizes
        # once it is known
        self.bytes_per_page = None
        # which scheduler phase is allocating ('admit' / 'window' /
        # 'cow' / None for direct users) — set by the owning engine
        # around its call sites purely so fault scripts can target one
        # phase ("pool dries mid-decode but admission still works")
        self.phase = None
        # which flight recorder the pool events land in — set by a
        # private-registry engine so N in-process replicas' alloc/free
        # trails never interleave (None = the process journal)
        self.journal = None

    def _record(self, kind, **fields):
        (self.journal if self.journal is not None
         else _journal.JOURNAL).record(kind, **fields)

    @property
    def usable(self):
        return self.num_blocks - 1

    def available(self):
        """Pages an alloc() can hand out: the free list plus the
        refcount-0 cached prefix pages (reclaimable on demand — the
        prefix cache never shrinks the allocatable pool)."""
        return len(self._free) + len(self._cached)

    def in_use(self):
        return len(self._ref)

    def cached(self):
        """Refcount-0 prefix pages parked on the LRU."""
        return len(self._cached)

    def shared(self):
        """Held pages with MORE than one reference."""
        return sum(1 for c in self._ref.values() if c > 1)

    def refcount(self, page):
        """Live references on `page` (0 = cached or free)."""
        return self._ref.get(page, 0)

    def utilization(self):
        """Held fraction of the usable pool (scratch page excluded;
        cached refcount-0 pages are reclaimable and do not count)."""
        return len(self._ref) / max(self.usable, 1)

    def alloc(self, n):
        """n page ids, or OutOfBlocks (the pool is untouched on
        failure — no partial allocation to unwind). When the free list
        alone cannot cover, refcount-0 cached prefix pages are evicted
        oldest-first (their index bindings drop) to make up the rest."""
        n = int(n)
        if n < 0:
            raise ValueError(f'cannot allocate {n} pages')
        if _faults.ACTIVE is not None:   # pre-check: alloc is per-page-op
            _faults.fire('alloc', n=n, free=len(self._free),
                         phase=self.phase)
        if n > len(self._free) + len(self._cached):
            raise OutOfBlocks(
                f'need {n} page(s), {len(self._free) + len(self._cached)} '
                f'free ({len(self._ref)}/{self.usable} in use)')
        harvest = max(0, n - len(self._free))
        if harvest:
            victims = list(itertools.islice(self._cached, harvest))
            if _faults.ACTIVE is not None:
                # seams fire BEFORE any mutation, so a scripted
                # prefix-evict fault leaves the pool untouched
                for p in victims:
                    _faults.fire('prefix_evict', page=p, phase=self.phase)
            for p in victims:
                self._unindex(p)
                del self._cached[p]
                self._free.append(p)
                self._record('prefix_evict', page=p, phase=self.phase)
            self.prefix_evictions += harvest
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        self.alloc_count += n
        self.high_water = max(self.high_water, len(self._ref))
        self._record('alloc', n=n, phase=self.phase,
                     free=len(self._free))
        return pages

    def free(self, pages):
        """Drop one reference per listed page. The last reference
        either returns the page to the free list or — when the page is
        prefix-indexed — parks it on the cached LRU (still hittable).
        Over-freeing and foreign ids raise — both are allocator-
        corruption bugs worth failing on."""
        pages = list(pages)
        if _faults.ACTIVE is not None:   # pre-check: free is per-page-op
            _faults.fire('free', pages=pages)
        drops: dict = {}
        for p in pages:
            drops[p] = drops.get(p, 0) + 1
        for p, k in drops.items():
            if self._ref.get(p, 0) < k:
                raise ValueError(
                    f'page {p} is not currently allocated '
                    f'(double-free or foreign id)')
        for p in pages:
            self._ref[p] -= 1
            if self._ref[p]:
                continue
            del self._ref[p]
            if p in self._hash_of:
                self._cached[p] = None       # LRU tail (newest)
            else:
                self._free.append(p)
        self.free_count += len(pages)
        self._record('free', n=len(pages))

    # -- prefix index ------------------------------------------------------

    def match_prefix(self, hashes):
        """Pages for the longest indexed leading run of `hashes`.
        Every returned page is held or cached RIGHT NOW — `share()`
        them before relying on the ids (an interleaved alloc could
        harvest a cached one)."""
        pages = []
        for h in hashes:
            p = self._index.get(h)
            if p is None:
                break
            pages.append(p)
        return pages

    def share(self, pages):
        """Take one more reference on each page (a prefix-cache hit):
        held pages refcount++, cached pages revive off the LRU.
        Sharing a free page is corruption and raises (nothing is
        mutated on failure)."""
        pages = list(pages)
        for p in pages:
            if p not in self._ref and p not in self._cached:
                raise ValueError(
                    f'page {p} is neither held nor cached — cannot share')
        for p in pages:
            if p in self._cached:
                del self._cached[p]
                self._ref[p] = 1
            else:
                self._ref[p] += 1
        self.prefix_shares += len(pages)
        self.high_water = max(self.high_water, len(self._ref))
        self._record('share', n=len(pages))
        return pages

    def register_prefix(self, page, h):
        """Bind chain hash `h` to a held page whose FULL block of
        prompt-token KV has been written. First writer wins: when the
        hash is already bound (a concurrent request computed the same
        block) the existing binding stays and this page simply remains
        unindexed. Returns True when the binding was recorded."""
        if page not in self._ref:
            raise ValueError(f'page {page} is not allocated')
        if h in self._index:
            return False
        self._index[h] = page
        self._hash_of[page] = h
        return True

    def cow(self, page):
        """Copy-on-write: hand the caller a private fresh page id for
        shared/indexed `page`. The caller must hold a reference on
        `page` and KEEPS it — that reference is the copy-pin: until
        the device rows are actually copied old -> new (the engine
        defers the copy into its next fused dispatch), freeing it
        would park an indexed source on the harvestable LRU, where a
        same-step allocation could hand it to another request whose
        prefill overwrites it BEFORE the copy reads it. Free the pin
        only once the copy has landed. Fires the alloc seam with
        phase='cow' so fault scripts can target exactly this path; on
        failure nothing changes."""
        if page not in self._ref:
            raise ValueError(f'page {page} is not allocated — cannot CoW')
        prev, self.phase = self.phase, 'cow'
        try:
            new = self.alloc(1)[0]
        finally:
            self.phase = prev
        self.cow_count += 1
        self._record('cow', src=page, new=new)
        return new

    def _unindex(self, page):
        h = self._hash_of.pop(page, None)
        if h is not None and self._index.get(h) == page:
            del self._index[h]

    def stats(self):
        s = {
            'num_blocks': self.num_blocks,
            'block_size': self.block_size,
            'in_use': self.in_use(),
            'free': self.available(),
            'utilization': round(self.utilization(), 4),
            'high_water': self.high_water,
            'allocs': self.alloc_count,
            'frees': self.free_count,
        }
        prefix = {
            'shared_pages': self.shared(),
            'cached_pages': len(self._cached),
            'indexed_pages': len(self._hash_of),
            'cow_pages': self.cow_count,
            'shares': self.prefix_shares,
            'evictions': self.prefix_evictions,
        }
        if self.bytes_per_page:
            # real units: page counts x per-page KV bytes across all
            # layers and both of k/v, at the pool dtype — what an HBM
            # budget is actually written in
            bpp = int(self.bytes_per_page)
            s['bytes_per_page'] = bpp
            s['bytes_total'] = self.num_blocks * bpp
            s['bytes_in_use'] = self.in_use() * bpp
            s['bytes_high_water'] = self.high_water * bpp
            prefix['bytes_shared'] = prefix['shared_pages'] * bpp
            prefix['bytes_cached'] = prefix['cached_pages'] * bpp
            prefix['bytes_cow'] = prefix['cow_pages'] * bpp
        s['prefix'] = prefix
        return s


def prompt_page_hashes(prompt, block_size):
    """Chain hashes for the FULL pages of `prompt` (one 16-byte
    blake2b digest per `block_size` tokens; each digest chains the
    previous one, so hash k covers the whole prefix through page k —
    a hit on page k implies the entire leading context matches). ONE
    batched token->bytes conversion covers the whole prompt — the
    admission hot path never converts per page in a loop (the
    tracelint host-sync discipline)."""
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    n = len(prompt) // block_size
    if not n:
        return []
    raw = np.ascontiguousarray(prompt[:n * block_size]).tobytes()
    step = 4 * block_size                 # int32 tokens
    out = []
    h = b'paddle_tpu.prefix.v1'
    for i in range(n):
        h = hashlib.blake2b(h + raw[i * step:(i + 1) * step],
                            digest_size=16).digest()
        out.append(h)
    return out


class Request:
    """One serving request. `generated` accumulates committed tokens
    across admissions (a preempted request keeps its prefix and resumes
    by re-prefill over prompt + prefix).

    `times` is the lifecycle trail: (event, perf_counter) pairs stamped
    at arrival / enqueued / admitted / prefill_dispatch / first_token /
    window / preempted / finished — always at points the host already
    owns (submission, scheduling, the one per-window commit sync), so
    collecting them costs no device round trip. The engine rolls them
    into the registry's ttft/itl/queue-wait histograms.

    Terminal states are `finished` / `failed` / `expired` /
    `cancelled`: `result` holds the output ids (finished only),
    `reason` the human-readable cause and `error` the underlying
    exception (failed only). `deadline` is an absolute perf_counter
    instant armed at submit from `deadline_s`."""

    __slots__ = ('rid', 'prompt', 'max_new_tokens', 'priority', 'generated',
                 'seq', 'state', 'admit_seq', 'times', 'enqueued_at',
                 'deadline', 'reason', 'error', 'result', 'page_hashes',
                 'temperature', 'top_k', 'top_p', 'sample_seed',
                 'spec_next', 'journal')

    def __init__(self, rid, prompt, max_new_tokens, priority,
                 temperature=0.0, top_k=0, top_p=1.0, sample_seed=None):
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.priority = int(priority)
        # per-request sampling params — SLOT STATE, not engine statics:
        # the engine uploads them as (SLOTS,) device data each window,
        # so a batch mixing greedy/top-k/nucleus rows never retraces.
        # `sample_seed` keys the stateless per-token PRNG chain (rid by
        # default — deterministic, and it rides snapshot/restore so
        # resumed sampled streams stay bit-equal). `spec_next` is the
        # speculative window's carried next-token choice (the verify's
        # committed pick, incl. the rejection resample), persisted so
        # preemption/restore resumes mid-stream bit-equal.
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.sample_seed = int(sample_seed if sample_seed is not None
                               else rid)
        self.spec_next = None
        self.generated: list = []
        # which flight recorder mark() writes to — the owning engine
        # re-binds it to its own journal before the first mark, so a
        # private-registry replica's request trails stay private
        # (None = the process journal)
        self.journal = None
        self.page_hashes = None  # full-prompt-page chain hashes, lazy
        self.seq = None          # arrival order, stamped by RequestQueue
        self.admit_seq = None    # last admission order (preemption ties)
        self.state = 'queued'
        self.times: list = []
        self.enqueued_at = None
        self.deadline = None     # absolute perf_counter instant, or None
        self.reason = None       # terminal cause (non-finished states)
        self.error = None        # underlying exception (failed only)
        self.result = None       # output ids (finished only)

    def mark(self, event, t=None, **fields):
        """Append one lifecycle timestamp (no-op while telemetry is
        off, so a disabled server keeps zero per-request overhead).
        Callers that already hold a fresh perf_counter (the window
        commit loop stamps every slot at one instant) pass it as `t`
        instead of re-reading the clock per request.

        Every mark is ALSO one flight-recorder event keyed by rid —
        `fields` carry the scheduler-decision context (slot, pages,
        reason, token counts) the journal's `trail(rid)` replays; the
        `times` list keeps only the (event, t) pairs the histograms
        roll up."""
        if _obs.enabled():
            t = time.perf_counter() if t is None else t
            self.times.append((event, t))
            (self.journal if self.journal is not None
             else _journal.JOURNAL).record(event, rid=self.rid, t=t,
                                           **fields)

    def when(self, event):
        """First timestamp for `event`, or None."""
        for e, t in self.times:
            if e == event:
                return t
        return None

    @property
    def remaining(self):
        return self.max_new_tokens - len(self.generated)

    @property
    def context_len(self):
        return len(self.prompt) + len(self.generated)


class RequestQueue:
    """Admission queue: higher `priority` first, FIFO within a
    priority. A preempted request keeps its original arrival seq, so it
    resumes ahead of later arrivals of the same priority.

    `remove()` is LAZY (cancel/shed mark the rid dead; the stale heap
    entry is discarded when it surfaces at peek/pop) so cancellation is
    O(1) and never reshuffles the heap under the scheduler."""

    def __init__(self):
        self._heap: list = []
        self._seq = itertools.count()
        self._dead: set = set()

    def push(self, req):
        if req.seq is None:
            req.seq = next(self._seq)
        if req.state != 'preempted':     # keep eviction observable
            req.state = 'queued'
        # queue-wait accounting starts here (covers first arrival AND
        # every preemption requeue — a resumed request waits again)
        req.enqueued_at = time.perf_counter()
        req.mark('enqueued', req.enqueued_at, state=req.state)
        heapq.heappush(self._heap, (-req.priority, req.seq, req))

    def remove(self, req):
        """Lazily drop a queued/preempted request (cancel / shed)."""
        self._dead.add(req.rid)

    def reset_seq(self, start):
        """Continue arrival order from `start` — restore() calls this
        after re-pushing a snapshot's requests (which keep their
        original seqs) so new submissions never tie or jump ahead of
        restored peers of equal priority."""
        self._seq = itertools.count(start)

    def _prune(self):
        while self._heap and self._heap[0][2].rid in self._dead:
            _, _, dropped = heapq.heappop(self._heap)
            self._dead.discard(dropped.rid)

    def peek(self):
        self._prune()
        return self._heap[0][2] if self._heap else None

    def pop(self):
        self._prune()
        return heapq.heappop(self._heap)[2]

    def __len__(self):
        return len(self._heap) - len(self._dead)

    def __iter__(self):
        """Live requests in pop order (snapshot serialization)."""
        return (r for _, _, r in sorted(self._heap)
                if r.rid not in self._dead)

    def live(self):
        """Live requests in heap (arbitrary) order, O(n). The
        submit-reject backpressure path scans the whole queue — the
        expiry sweep filters by deadline, the shed scan takes a min()
        — and neither needs __iter__'s O(n log n) pop-order sort."""
        return (r for _, _, r in self._heap if r.rid not in self._dead)


# ---------------------------------------------------------------------------
# Module-level compiled steps (the persistent jit cache, PR-1 style)
# ---------------------------------------------------------------------------

def _pin(x, *spec_entries):
    """Sharding pin for the fused serving dispatches: a
    `with_sharding_constraint` that degrades to identity when no mesh
    is active (the single-device engines trace through here with the
    graph unchanged). Under a TP mesh the pins keep every dispatch
    OUTPUT on the layout its matching input was uploaded with — pools
    kv-head-sharded over 'tp', everything host-facing replicated — so
    the jit cache key is stable from the very first call (warmup and
    live dispatch compile the same executable; zero steady-state
    retraces holds on the sharded engine exactly as it does on one
    chip)."""
    from ..distributed.mp_layers import sharding_constraint

    return sharding_constraint(x, *spec_entries)


def _pin_pages(pages):
    """Pin every page pool to the kv-head 'tp' split (identity without
    a mesh; clamps to replicated when kv_heads does not divide tp —
    the same GQA fallback `init_paged_cache` places with). Every field
    of both pool containers (PagedKVCache kp/vp, QuantPagedKVCache
    kp/vp/ks/vs) carries the kv-head dim at axis 1, so one spec pins
    them all."""
    return [type(pc)(*[_pin(f, None, 'tp') for f in pc]) for pc in pages]


def _pool_quant(pages):
    """Whether the page pools are int8 (QuantPagedKVCache — per-row
    scale fields ride along with the data pages)."""
    return hasattr(pages[0], 'ks')


def _tmp_cache(model, pages, K, Sb):
    """Throwaway contiguous temp cache for a fused multi-token body
    (admission prefill, chunk continuation, speculative verify), in the
    POOL's quantization world: plain bf16 (k, v) pairs for PagedKVCache
    pools; RowQuantKVCache for int8 pools — the forward then writes
    per-row-quantized rows and attends dequantized ones, so every value
    it sees is exactly the int8-roundtripped value a paged decode step
    sees. That shared world is what keeps int8 greedy streams bit-equal
    across monolithic prefill, chunked prefill, speculative windows,
    preemption re-prefill, and prefix-cache hits."""
    if _pool_quant(pages):
        from ..models.generation import RowQuantKVCache

        _, Hkv, _, D = pages[0].kp.shape
        z8 = jnp.zeros((K, Sb, Hkv, D), jnp.int8)
        zs = jnp.zeros((K, Sb, Hkv), jnp.float32)
        return [RowQuantKVCache(z8, z8, zs, zs) for _ in pages]
    return model.init_cache(K, Sb)


def _pool_scatter(pc, tmp_entry, pflat, sflat, take=None):
    """Scatter one layer's temp-cache rows into its page pool at
    (pflat, sflat) flat (page, slot) targets. `take` (K, S) optionally
    re-gathers a sub-range of the temp cache first (the chunk/verify
    bodies scatter only the rows they wrote, clamped in-range). Int8
    pools copy int8 bytes AND the per-row scales — no requantization,
    so the pool holds exactly what the temp-cache write produced."""
    if hasattr(pc, 'ks'):
        kq, vq, ks, vs = tmp_entry
        if take is not None:
            idx4 = take[:, :, None, None]
            idx3 = take[:, :, None]
            kq = jnp.take_along_axis(kq, idx4, axis=1)
            vq = jnp.take_along_axis(vq, idx4, axis=1)
            ks = jnp.take_along_axis(ks, idx3, axis=1)
            vs = jnp.take_along_axis(vs, idx3, axis=1)
        rows = (pflat.shape[0],) + kq.shape[2:]
        srows = (pflat.shape[0],) + ks.shape[2:]
        return type(pc)(
            pc.kp.at[pflat, :, sflat, :].set(kq.reshape(rows)),
            pc.vp.at[pflat, :, sflat, :].set(vq.reshape(rows)),
            pc.ks.at[pflat, :, sflat].set(ks.reshape(srows)),
            pc.vs.at[pflat, :, sflat].set(vs.reshape(srows)))
    k, v = tmp_entry
    if take is not None:
        idx4 = take[:, :, None, None]
        k = jnp.take_along_axis(k, idx4, axis=1)
        v = jnp.take_along_axis(v, idx4, axis=1)
    rows = (pflat.shape[0],) + k.shape[2:]
    return type(pc)(
        pc.kp.at[pflat, :, sflat, :].set(k.reshape(rows).astype(pc.kp.dtype)),
        pc.vp.at[pflat, :, sflat, :].set(v.reshape(rows).astype(pc.vp.dtype)))


def _pool_gather(pages, btabs, st, Sb):
    """Gather each row's committed prefix [0, st[b]) out of its pages
    into a contiguous temp cache of static length Sb (positions >=
    st read the scratch page — never attended, the per-row mask stops
    at the write position). Int8 pools gather int8 bytes + per-row
    scales into a RowQuantKVCache, so the continuation forward attends
    the SAME roundtripped values a paged decode step would."""
    from ..models.generation import RowQuantKVCache

    K = btabs.shape[0]
    bs = pages[0].kp.shape[2]
    maxb = btabs.shape[1]
    s = jnp.arange(Sb)
    blk = jnp.minimum(s // bs, maxb - 1)
    gpage = jnp.take_along_axis(
        btabs, jnp.broadcast_to(blk[None, :], (K, Sb)), axis=1)
    gpage = jnp.where(s[None, :] < st[:, None], gpage, 0)
    soff = jnp.broadcast_to((s % bs)[None, :], (K, Sb))
    if _pool_quant(pages):
        return [RowQuantKVCache(pc.kp[gpage, :, soff, :],
                                pc.vp[gpage, :, soff, :],
                                pc.ks[gpage, :, soff],
                                pc.vs[gpage, :, soff])
                for pc in pages]
    return [(pc.kp[gpage, :, soff, :], pc.vp[gpage, :, soff, :])
            for pc in pages]


# per-request sampling randomness is STATELESS: the key for one
# sampled event is fold_in(fold_in(PRNGKey(request seed), generated
# token index), sub-stream id). A resumed request (preemption requeue,
# snapshot/restore) re-derives exactly the keys the uninterrupted run
# used — sampled streams stay bit-equal with no carried key state.
_SUB_PROPOSE = 0      # sampling a token (decode windows, draft props)
_SUB_ACCEPT = 1       # the speculative accept coin
_SUB_RESAMPLE = 2     # the speculative rejection resample


def _row_keys(seed, gen, sub):
    """One PRNG key per batch row: fold the row's generated-token index
    and the sub-stream id into its request seed."""
    def one(s, n):
        return jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(s), n), sub)

    return jax.vmap(one)(jnp.asarray(seed, jnp.uint32),
                         jnp.asarray(gen, jnp.int32))


def _sample_rows(logits, temp, topk, topp, keys):
    """Per-row next-token choice over one batch of logits: greedy
    argmax where temp == 0, categorical over the row's filtered /
    tempered distribution elsewhere — all branches live in ONE trace,
    so a batch mixing greedy and sampled rows (the per-request
    sampling contract) never retraces as the mix changes. (The unused
    dist output is dead code XLA eliminates — one sampling body, no
    drift between decode windows and draft proposals.)"""
    return _sample_rows_dist(logits, temp, topk, topp, keys)[0]


def _filtered_dist(logits, temp, topk, topp):
    """Per-row filtered/tempered probability dist over (K, V) logits
    (rows with temp == 0 use temp 1 — their dist is never consumed;
    the greedy rule takes argmax instead)."""
    from ..models.generation import filter_logits_batched

    lg = logits.astype(jnp.float32)
    safe_t = jnp.where(temp > 0, temp, 1.0)
    return jax.nn.softmax(
        filter_logits_batched(lg / safe_t[:, None], topk, topp), -1)


def _sample_rows_dist(logits, temp, topk, topp, keys):
    """`_sample_rows` + the row's filtered dist from ONE shared filter
    pass (the speculative draft loop needs both per proposal — two
    separate calls would double the full-vocab sorts in the hottest
    scan of the spec window)."""
    from ..models.generation import filter_logits_batched

    lg = logits.astype(jnp.float32)
    greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
    safe_t = jnp.where(temp > 0, temp, 1.0)
    f = filter_logits_batched(lg / safe_t[:, None], topk, topp)
    sampled = jax.vmap(jax.random.categorical)(keys, f).astype(jnp.int32)
    return jnp.where(temp > 0, sampled, greedy), jax.nn.softmax(f, -1)


def _prefill_kv(model, pages, ids, real_len, btabs):
    """Bucketed BATCHED admission prefill INTO pages (traced body,
    shared by the standalone `_paged_prefill` jit and the fused
    `_serve_step`/`_serve_spec_step`): run the model once over up to
    max_slots RIGHT-padded prompts (K, Sb) with a throwaway contiguous
    cache in the pool's quantization world (the standard causal path —
    pad rows come after the real tokens, so rows < real_len never see
    them), then scatter every K/V row into its request's pages: row s
    of request b lands in page btabs[b, s // BS] slot s % BS, pad and
    DUMMY rows (real_len == 0) land on the scratch page 0. The batch
    width is FIXED at max_slots and real lengths ride as device data,
    so one compilation per bucket serves every admission count and
    every prompt length in the bucket. Returns (per-row last-token
    logits (K, V), pages)."""
    K, Sb = ids.shape
    tmp = _tmp_cache(model, pages, K, Sb)
    logits, tmp = model(ids, caches=tmp, cache_index=0)
    rl = jnp.reshape(jnp.asarray(real_len, jnp.int32), (K,))
    last = jnp.take_along_axis(
        logits, jnp.maximum(rl - 1, 0)[:, None, None], axis=1)[:, 0]
    bs = pages[0].kp.shape[2]
    maxb = btabs.shape[1]
    s = jnp.arange(Sb)
    blk = jnp.minimum(s // bs, maxb - 1)
    page = jnp.where(s[None, :] < rl[:, None],
                     jnp.take_along_axis(btabs, blk[None, :], axis=1),
                     0)                                       # (K, Sb)
    pflat = page.reshape(-1)
    sflat = jnp.broadcast_to(s % bs, (K, Sb)).reshape(-1)
    out_pages = [_pool_scatter(pc, t, pflat, sflat)
                 for t, pc in zip(tmp, pages)]
    return last, out_pages


def _prefill_body(model, pages, last_logits, ids, real_len, btabs, slots):
    """`_prefill_kv` plus the per-slot logits commit: each request's
    next-token logits land in its slot's row of `last_logits` (dummy
    rows carry slot == SLOTS, dropped by the out-of-bounds scatter)."""
    last, out_pages = _prefill_kv(model, pages, ids, real_len, btabs)
    last_logits = last_logits.at[slots].set(
        last.astype(last_logits.dtype), mode='drop')
    return _pin(last_logits), _pin_pages(out_pages)


def _window_body(model, pages, last_logits, btab, ctx, live, budget,
                 temp, topk, topp, seed, plen, *, window, eos_token_id,
                 forced_tok=None, forced=None):
    """One decode window for the whole fixed-slot batch as ONE compiled
    lax.scan (traced body, shared by `_serve_window` and the fused
    `_serve_step`): per step, choose every slot's next token from the
    carried logits under ITS OWN sampling params (temp/topk/topp/seed
    ride as (SLOTS,) device data — a batch mixing greedy and sampled
    requests shares this one trace, and changing the mix never
    retraces), step the model over the paged caches (per-row write
    positions = ctx, attention through the block tables), advance the
    committed length of live rows. Sampled rows draw their key
    statelessly from (request seed, generated-token index), so a
    resumed request replays exactly the keys the uninterrupted run
    used. Rows freeze when they hit eos, burn their budget, or were
    never live (empty slots): frozen rows still ride through the
    static-shape forward but write only to their frozen position / the
    scratch page and commit nothing — exactly how requests leave the
    batch without changing a traced shape. Returns (tokens (SLOTS,
    window), last_logits, pages, ctx); the host reads the tokens ONCE
    per window and does all bookkeeping there."""
    pad_tok = eos_token_id if eos_token_id is not None else 0
    plen = jnp.asarray(plen, jnp.int32)

    def step(carry, t):
        last_logits, pages, ctx, finished = carry
        keys = _row_keys(seed, ctx - plen, _SUB_PROPOSE)
        tok = _sample_rows(last_logits, temp, topk, topp, keys)
        if forced is not None:
            # a speculative engine's chunk step: rows carrying a
            # pending verify-chosen next-token (incl. the rejection
            # RESAMPLE for sampled rows) consume it as this window's
            # FIRST token instead of re-sampling — the carried choice
            # is the committed one, whatever dispatch shape lands it
            tok = jnp.where(forced & (t == 0),
                            jnp.asarray(forced_tok, tok.dtype), tok)
        frozen = finished | (t >= budget)
        tok = jnp.where(frozen, jnp.asarray(pad_tok, tok.dtype), tok)
        commit = ~frozen
        if eos_token_id is not None:
            finished = finished | (commit & (tok == eos_token_id))
        logits, pages = model(tok[:, None], caches=pages,
                              kv_write_pos=ctx, block_tables=btab)
        ctx = ctx + commit.astype(jnp.int32)
        return (logits[:, -1, :], pages, ctx, finished), tok

    state = (last_logits, pages, jnp.asarray(ctx, jnp.int32), ~live)
    (last_logits, pages, ctx, _), toks = jax.lax.scan(
        step, state, jnp.arange(window, dtype=jnp.int32))
    return _pin(toks.T), _pin(last_logits), _pin_pages(pages), _pin(ctx)


@functools.partial(jax.jit, donate_argnames=('pages', 'last_logits'))
def _paged_prefill(model, pages, last_logits, ids, real_len, btabs, slots):
    """Standalone admission prefill (see _prefill_body) — used only for
    the rare step that admits across SEVERAL buckets at once; the first
    (largest) bucket group rides fused inside _serve_step."""
    _count_trace('serve_prefill')
    return _prefill_body(model, pages, last_logits, ids, real_len, btabs,
                         slots)


@functools.partial(
    jax.jit, donate_argnames=('pages', 'last_logits'),
    static_argnames=('window', 'eos_token_id'))
def _serve_window(model, pages, last_logits, btab, ctx, live, budget,
                  temp, topk, topp, seed, plen, *, window, eos_token_id):
    """A pure decode window (no admissions this step): see
    _window_body."""
    _count_trace('serve_window')
    return _window_body(model, pages, last_logits, btab, ctx, live,
                        budget, temp, topk, topp, seed, plen,
                        window=window, eos_token_id=eos_token_id)


@functools.partial(
    jax.jit, donate_argnames=('pages', 'last_logits'),
    static_argnames=('window', 'eos_token_id'))
def _serve_step(model, pages, last_logits, ids, real_len, btabs, slots,
                btab, ctx, live, budget, temp, topk, topp, seed, plen, *,
                window, eos_token_id):
    """THE scheduler iteration as one fused jitted dispatch: freshly
    admitted rows bucket-prefill into their newly allocated pages
    (_prefill_body), then every slot — new and old — decodes a window
    through the paged kernel (_window_body). One compilation per
    (bucket, window) pair covers every admission count; a step with no
    admissions uses _serve_window instead."""
    _count_trace('serve_step')
    last_logits, pages = _prefill_body(model, pages, last_logits, ids,
                                       real_len, btabs, slots)
    return _window_body(model, pages, last_logits, btab, ctx, live,
                        budget, temp, topk, topp, seed, plen,
                        window=window, eos_token_id=eos_token_id)


def _spec_window_impl(target, draft, pages, dpages, last_logits,
                      forced_tok, forced, btab, ctx, live, budget, temp,
                      topk, topp, seed, plen, *, k, ctx_bucket,
                      eos_token_id):
    """One speculative propose/verify/commit window over the fixed-slot
    batch (traced body of `_serve_spec_window` / `_serve_spec_step`) —
    the DecodeEngine's fused window contract composed with the paged
    pool and per-request sampling:

      1. candidate 0: the previous window's carried next-token
         (`forced_tok` where `forced` — the committed choice the verify
         already made, incl. the rejection RESAMPLE for sampled rows)
         or, on a slot's first window after admission, a per-row
         sample/argmax off the prefill's `last_logits`;
      2. draft propose: k+1 single-token steps through the DRAFT's
         paged pools (same block tables, same kv_write_pos offsets —
         the k-th proposal's own KV row is written too, the
         DecodeEngine pattern), each proposal chosen under the row's
         own sampling params;
      3. target verify: ONE (K, k+1) forward over the target with the
         committed prefix GATHERED from its pages into a contiguous
         temp cache of static length `ctx_bucket` (the chunked-prefill
         machinery — per-row kv_write_pos offsets, zero model changes);
      4. commit rule per row: greedy rows accept the longest draft
         prefix the target's argmax agrees with; sampled rows run the
         Leviathan/Chen accept coin min(1, pt/pd) per position with a
         rejection RESAMPLE from the normalised residual (pt - pd)+ —
         the output law equals sampling the target directly. ncommit =
         accepted + 1, clamped by budget and truncated at eos;
      5. only the committed rows' target K/V scatter back into pages
         (rejected rows land on the scratch page), so the pages hold
         exactly what a non-speculative step would have written —
         greedy streams stay bit-equal spec-on vs spec-off.

    Returns (cand (K, k+1), ncommit (K,), next_tok (K,), last_logits,
    pages, dpages, ctx): `cand[:ncommit]` are this window's committed
    tokens; `next_tok` is the carried choice the host feeds back as
    `forced_tok` (and persists per request, so preemption and
    snapshot/restore resume sampled streams bit-equal)."""
    K, V = last_logits.shape
    ctx = jnp.asarray(ctx, jnp.int32)
    plen = jnp.asarray(plen, jnp.int32)
    budget = jnp.asarray(budget, jnp.int32)
    gen0 = ctx - plen
    sampled_row = temp > 0
    keys0 = _row_keys(seed, gen0, _SUB_PROPOSE)
    cand0 = jnp.where(forced, jnp.asarray(forced_tok, jnp.int32),
                      _sample_rows(last_logits, temp, topk, topp, keys0))

    def dstep(carry, i):
        tok, dpages = carry
        dlogits, dpages = draft(tok[:, None], caches=dpages,
                                kv_write_pos=ctx + i, block_tables=btab)
        gkeys = _row_keys(seed, gen0 + i + 1, _SUB_PROPOSE)
        nxt, pd = _sample_rows_dist(dlogits[:, -1, :], temp, topk,
                                    topp, gkeys)
        return (nxt, dpages), (nxt, pd)

    (_, dpages), (toks, pds) = jax.lax.scan(
        dstep, (cand0, dpages), jnp.arange(k + 1, dtype=jnp.int32))
    drafts = jnp.swapaxes(toks[:k], 0, 1)                  # (K, k)
    pd = jnp.swapaxes(pds[:k], 0, 1)                       # (K, k, V)
    window_ids = jnp.concatenate([cand0[:, None], drafts], axis=1)
    # verify: the whole (K, k+1) window in one target forward over the
    # gathered contiguous prefix (rows write at ctx..ctx+k inside tmp)
    tmp = _pool_gather(pages, btab, ctx, ctx_bucket)
    tlogits, tmp = target(window_ids, caches=tmp, kv_write_pos=ctx)
    tlg = tlogits.astype(jnp.float32)                      # (K, k+1, V)
    tchoice = jnp.argmax(tlg, axis=-1).astype(jnp.int32)   # (K, k+1)
    # per-row filtered target dists at every window position
    flat = tlg.reshape(K * (k + 1), V)
    rep = lambda x: jnp.repeat(x, k + 1, axis=0)  # noqa: E731
    pt = _filtered_dist(flat, rep(temp), rep(topk),
                        rep(topp)).reshape(K, k + 1, V)
    # accept rule per draft position
    greedy_acc = drafts == tchoice[:, :k]
    px_t = jnp.take_along_axis(pt[:, :k, :], drafts[:, :, None],
                               axis=-1)[..., 0]            # (K, k)
    px_d = jnp.take_along_axis(pd, drafts[:, :, None], axis=-1)[..., 0]

    def coin(s, n):
        kk = jax.random.fold_in(jax.random.fold_in(
            jax.random.PRNGKey(s), n), _SUB_ACCEPT)
        return jax.random.uniform(kk)

    u = jax.vmap(lambda s_, n0: jax.vmap(
        lambda i: coin(s_, n0 + i + 1))(jnp.arange(k)))(
            jnp.asarray(seed, jnp.uint32), gen0)           # (K, k)
    samp_acc = u < jnp.minimum(1.0, px_t / jnp.maximum(px_d, 1e-30))
    acc = jnp.where(sampled_row[:, None], samp_acc, greedy_acc)
    m = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)
    # the carried next token: greedy rows take the target's choice at
    # the first disagreement; sampled rows resample from the residual
    # (pt - pd)+ — a full-accept row's pd pads to 0, so its residual
    # IS pt_k (the bonus-token rule falls out of the same expression)
    pt_m = jnp.take_along_axis(pt, m[:, None, None], axis=1)[:, 0]
    pd_pad = jnp.concatenate([pd, jnp.zeros((K, 1, V), pd.dtype)],
                             axis=1)
    pd_m = jnp.take_along_axis(pd_pad, m[:, None, None], axis=1)[:, 0]
    res = jnp.maximum(pt_m - pd_m, 0.0)
    rs = jnp.sum(res, axis=-1, keepdims=True)
    res = jnp.where(rs > 0, res / jnp.maximum(rs, 1e-30), pt_m)
    rkeys = _row_keys(seed, gen0 + m + 1, _SUB_RESAMPLE)
    sampled_next = jax.vmap(jax.random.categorical)(
        rkeys, jnp.log(jnp.maximum(res, 1e-30))).astype(jnp.int32)
    greedy_next = jnp.take_along_axis(tchoice, m[:, None],
                                      axis=1)[:, 0]
    next_tok = jnp.where(sampled_row, sampled_next, greedy_next)
    # commit count: accepted prefix + the candidate that started it,
    # clamped by the row's remaining budget, truncated at the first
    # eos inside the committed prefix, zero for dead rows
    nc = jnp.minimum(m + 1, budget)
    if eos_token_id is not None:
        iseos = window_ids == eos_token_id
        first = jnp.argmax(iseos, axis=1)
        nc = jnp.where(jnp.any(iseos, axis=1) & (first < nc),
                       first + 1, nc)
    nc = jnp.where(live, nc, 0)
    # scatter ONLY the committed rows' target K/V back into pages
    # (rejected/beyond-budget rows go to the scratch page — the next
    # window rewrites those positions anyway)
    bs = pages[0].kp.shape[2]
    maxb = btab.shape[1]
    i = jnp.arange(k + 1)
    wpos = ctx[:, None] + i[None, :]                       # (K, k+1)
    wblk = jnp.minimum(wpos // bs, maxb - 1)
    wpage = jnp.where(i[None, :] < nc[:, None],
                      jnp.take_along_axis(btab, wblk, axis=1), 0)
    pflat = wpage.reshape(-1)
    sflat = (wpos % bs).reshape(-1)
    take = jnp.minimum(wpos, ctx_bucket - 1)
    pages = [_pool_scatter(pc, t, pflat, sflat, take=take)
             for t, pc in zip(tmp, pages)]
    # next window's sampling base for rows that keep going: the
    # target's logits at the last committed position (rows that stop —
    # eos/budget — are retired by the host before the next window)
    last = jnp.take_along_axis(
        tlg, jnp.maximum(nc - 1, 0)[:, None, None], axis=1)[:, 0]
    last_logits = jnp.where(live[:, None],
                            last.astype(last_logits.dtype), last_logits)
    ctx = ctx + nc
    return (_pin(jnp.asarray(window_ids, jnp.int32)), _pin(nc),
            _pin(next_tok), _pin(last_logits), _pin_pages(pages),
            _pin_pages(dpages), _pin(ctx))


@functools.partial(
    jax.jit, donate_argnames=('pages', 'dpages', 'last_logits'),
    static_argnames=('k', 'ctx_bucket', 'eos_token_id'))
def _serve_spec_window(target, draft, pages, dpages, last_logits,
                       forced_tok, forced, btab, ctx, live, budget, temp,
                       topk, topp, seed, plen, *, k, ctx_bucket,
                       eos_token_id):
    """A pure speculative window (no admissions this step): see
    _spec_window_impl."""
    _count_trace('serve_spec_window')
    return _spec_window_impl(target, draft, pages, dpages, last_logits,
                             forced_tok, forced, btab, ctx, live, budget,
                             temp, topk, topp, seed, plen, k=k,
                             ctx_bucket=ctx_bucket,
                             eos_token_id=eos_token_id)


@functools.partial(
    jax.jit, donate_argnames=('pages', 'dpages', 'last_logits'),
    static_argnames=('k', 'ctx_bucket', 'eos_token_id'))
def _serve_spec_step(target, draft, pages, dpages, last_logits, ids,
                     real_len, btabs, slots, forced_tok, forced, btab,
                     ctx, live, budget, temp, topk, topp, seed, plen, *,
                     k, ctx_bucket, eos_token_id):
    """The speculative scheduler iteration as one fused jitted
    dispatch: freshly admitted rows bucket-prefill into their pages on
    BOTH models (the draft's pool mirrors the target's block tables,
    so one allocator serves both), then every slot runs a
    propose/verify/commit window (_spec_window_impl). One compilation
    per (k, bucket, ctx bucket) triple covers every admission count
    and sampling mix."""
    _count_trace('serve_spec_step')
    last_logits, pages = _prefill_body(target, pages, last_logits, ids,
                                       real_len, btabs, slots)
    _, dpages = _prefill_kv(draft, dpages, ids, real_len, btabs)
    dpages = _pin_pages(dpages)
    return _spec_window_impl(target, draft, pages, dpages, last_logits,
                             forced_tok, forced, btab, ctx, live, budget,
                             temp, topk, topp, seed, plen, k=k,
                             ctx_bucket=ctx_bucket,
                             eos_token_id=eos_token_id)


def _chunk_body(model, pages, last_logits, ids, chunk_len, start, btabs,
                slots, cow_src, cow_dst, *, ctx_bucket):
    """Chunked / continuation prefill INTO pages (traced body, fused
    ahead of the decode window by `_serve_chunk_step`): each row b
    already owns positions [0, start[b]) of its context in its pages —
    a prior chunk's output, or shared prefix-cache pages — and appends
    chunk_len[b] new tokens at positions [start[b], start[b] +
    chunk_len[b]).

    The model needs no paged-prefill support: each row's committed
    prefix K/V is GATHERED out of its pages into a throwaway
    contiguous cache of static length `ctx_bucket` (the bucket of the
    largest end position in the batch), the chunk runs through the
    standard per-row-offset forward (`kv_write_pos` — the speculative-
    verify machinery: causal within the chunk, full attention over the
    gathered prefix), and the new K/V rows scatter back into pages
    exactly like `_prefill_body`. Rows whose chunk COMPLETES their
    context carry their slot id in `slots` and commit next-token
    logits; still-prefilling and dummy rows carry max_slots and are
    dropped by the OOB scatter — so a chunked request occupies its
    slot but emits nothing until its last chunk commits.

    `cow_src`/`cow_dst` apply the copy-on-write page copies the
    scheduler armed this step (dst := src, FIRST, so the gather and
    the scatter below both see the private copy through the already-
    rewritten block tables); rows with no pending copy carry (0, 0) —
    a harmless scratch-page self-copy."""
    K, Cb = ids.shape
    bs = pages[0].kp.shape[2]
    maxb = btabs.shape[1]
    Sb = int(ctx_bucket)
    cl = jnp.reshape(jnp.asarray(chunk_len, jnp.int32), (K,))
    st = jnp.reshape(jnp.asarray(start, jnp.int32), (K,))
    # CoW copies first (every pool field — int8 pools copy the per-row
    # scale rows with their page, so a shared page's quantization
    # survives the private fork byte for byte)
    pages = [type(pc)(*[f.at[cow_dst].set(f[cow_src]) for f in pc])
             for pc in pages]
    # gather each row's prefix rows [0, start) into a contiguous
    # (K, Sb, ...) temp cache in the pool's quantization world;
    # positions >= start read the scratch page (never attended: the
    # per-row causal mask stops at qpos)
    tmp = _pool_gather(pages, btabs, st, Sb)
    logits, tmp = model(ids, caches=tmp, kv_write_pos=st)
    last = jnp.take_along_axis(
        logits, jnp.maximum(cl - 1, 0)[:, None, None], axis=1)[:, 0]
    # scatter the chunk's K/V rows back into pages: position start + i
    # of row b lands in page btabs[b, (start+i) // bs] slot (start+i) %
    # bs; pad and dummy rows (i >= chunk_len) land on the scratch page
    i = jnp.arange(Cb)
    wpos = st[:, None] + i[None, :]                        # (K, Cb)
    wblk = jnp.minimum(wpos // bs, maxb - 1)
    wpage = jnp.where(i[None, :] < cl[:, None],
                      jnp.take_along_axis(btabs, wblk, axis=1), 0)
    pflat = wpage.reshape(-1)
    sflat = (wpos % bs).reshape(-1)
    take = jnp.minimum(wpos, Sb - 1)
    out_pages = [_pool_scatter(pc, t, pflat, sflat, take=take)
                 for t, pc in zip(tmp, pages)]
    last_logits = last_logits.at[slots].set(
        last.astype(last_logits.dtype), mode='drop')
    return _pin(last_logits), _pin_pages(out_pages)


@functools.partial(
    jax.jit, donate_argnames=('pages', 'last_logits'),
    static_argnames=('ctx_bucket', 'window', 'eos_token_id'))
def _serve_chunk_step(model, pages, last_logits, ids, chunk_len, start,
                      btabs, slots, cow_src, cow_dst, btab, ctx, live,
                      budget, temp, topk, topp, seed, plen, forced_tok,
                      forced, *, ctx_bucket, window, eos_token_id):
    """The chunked-prefill scheduler iteration as one fused jitted
    dispatch: every in-progress chunked/continuation row appends its
    chunk into its pages (_chunk_body — CoW copies first, prefix
    gathered from pages, completing rows commit their logits), then
    every slot decodes a window (_window_body; still-prefilling rows
    ride frozen on the scratch page). One compilation per (window,
    chunk bucket, context bucket) triple covers every row count, chunk
    length, and prefill progress — a long-prompt flood never changes a
    traced shape."""
    _count_trace('serve_chunk_step')
    last_logits, pages = _chunk_body(model, pages, last_logits, ids,
                                     chunk_len, start, btabs, slots,
                                     cow_src, cow_dst,
                                     ctx_bucket=ctx_bucket)
    return _window_body(model, pages, last_logits, btab, ctx, live,
                        budget, temp, topk, topp, seed, plen,
                        window=window, eos_token_id=eos_token_id,
                        forced_tok=forced_tok, forced=forced)


@functools.partial(
    jax.jit, donate_argnames=('dpages', 'dlogits'),
    static_argnames=('ctx_bucket',))
def _draft_chunk(draft, dpages, dlogits, ids, chunk_len, start, btabs,
                 slots, cow_src, cow_dst, *, ctx_bucket):
    """Draft-side mirror of the chunk/continuation prefill: a
    speculative engine must keep the DRAFT's pages current through
    every admission path, or chunk-admitted and prefix-hit rows would
    draft against missing prompt KV and speculation would silently
    degrade to pure overhead (accept rate collapse with no error).
    Same body as the target's chunk leg — CoW copies fork the draft's
    pages too, the gathered prefix is the draft's own — with the
    logits commit dropped by all-dummy slot indices (`dlogits` is a
    throwaway donated buffer)."""
    _count_trace('serve_draft_chunk')
    return _chunk_body(draft, dpages, dlogits, ids, chunk_len, start,
                       btabs, slots, cow_src, cow_dst,
                       ctx_bucket=ctx_bucket)


@functools.partial(jax.jit, static_argnames=('ctx_bucket',))
def _kv_export(pages, btabs, st, *, ctx_bucket):
    """Gather ONE request's committed KV prefix [0, st[0]) out of its
    pages into contiguous per-layer rows (the `_serve_chunk_step`
    gather path at K=1) — the device half of `export_kv`. No donation:
    the source pool must survive the export (the request keeps serving
    until its owner decides the handoff). Outputs pin REPLICATED: under
    a tp mesh this is the all-gather that reassembles the kv-head
    shards into one host-fetchable, degree-agnostic blob (the
    migration shardlint suite budgets it exactly). Int8 pools gather
    int8 bytes + per-row scales, so the blob reproduces the pool
    bit-for-bit at half the bf16 bytes."""
    _count_trace('serve_export')
    tmp = _pool_gather(pages, btabs, st, ctx_bucket)
    out = []
    for t in tmp:
        fs = [_pin(f) for f in t]
        out.append(type(t)(*fs) if hasattr(t, '_fields') else tuple(fs))
    return out


@functools.partial(jax.jit, donate_argnames=('pages',),
                   static_argnames=('ctx_bucket',))
def _kv_import(pages, blob, pflat, sflat, *, ctx_bucket):
    """Scatter an exported blob's contiguous rows into this pool's
    pages at flat (page, slot) targets — the device half of
    `import_kv`, riding the same `.at[...].set` write the chunk bodies
    commit through. Rows the host masked (past the export length, or
    covered by shared prefix pages) land on the reserved scratch page.
    The replicated blob re-shards on write under a tp mesh (each shard
    keeps its own kv-head rows — a slice, not a collective), so a
    blob exported at one tp degree imports at any other."""
    del ctx_bucket           # shapes carry it; static keys the registry
    _count_trace('serve_import')
    out = [_pool_scatter(pc, t, pflat, sflat)
           for t, pc in zip(blob, pages)]
    return _pin_pages(out)


def _ceil_div(a, b):
    return -(-a // b)


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

class ServingEngine:
    """Continuous-batching serving over one model.

        engine = ServingEngine(model, max_slots=8, num_blocks=...,
                               max_new_tokens=64, eos_token_id=2)
        rid = engine.submit(prompt_ids)          # 1-D int array
        engine.run()                             # drain queue + batch
        out = engine.result(rid)                 # (S + max_new,) ids

        outs = engine.serve(list_of_prompts)     # submit+run+collect

    Greedy outputs per request are exactly `DecodeEngine.generate`'s
    batch-1 outputs (eos-padded to max_new_tokens, prompt echoed back).
    The model must accept `block_tables` in its cached forward (the
    Llama family does) and must not use sliding-window attention.
    """

    def __init__(self, model, max_slots=8, block_size=16, num_blocks=None,
                 max_context_len=None, max_new_tokens=32, decode_window=8,
                 temperature=0.0, top_k=0, top_p=1.0, eos_token_id=None,
                 buckets=None, max_queue=None, admit_watermark=1.0,
                 shed_policy='reject', max_terminal=1024,
                 prefix_cache=False, prefill_chunk=None,
                 postmortem_dir=None, mesh=None, tp=None,
                 ops_port=None, ops_host='127.0.0.1', watchdog=None,
                 slo_rules=None, ts_interval_s=None,
                 draft=None, num_draft_tokens=4, kv_cache_dtype=None,
                 phase_role='monolithic', metrics_registry=None,
                 journal=None, rid_start=0):
        params = inspect.signature(model.forward).parameters
        # telemetry scope (docs/observability.md#per-replica-scopes):
        # metrics_registry gives this engine a PRIVATE MetricsRegistry
        # — every serve.*/pool.* series, the windowed rate gauges, and
        # the watchdog's health series land there instead of the
        # process registry, so N in-process replicas (the fleet shape)
        # never merge their series. A private registry implies a
        # private flight-recorder journal too (request trails, pool
        # events) unless `journal=` passes one explicitly. None/None =
        # the process globals, prior behavior bit-identical.
        self._registry = (metrics_registry if metrics_registry is not None
                          else _obs.REGISTRY)
        if journal is not None:
            self._jr = journal
        elif metrics_registry is not None:
            self._jr = _journal.Journal()
        else:
            self._jr = _journal.JOURNAL
        # rid_start offsets this engine's request-id space: fleet
        # replicas take disjoint strides so a request keeps its rid
        # across a drain-migration or kill-resurrection hop to another
        # replica (rids are the join key for trails and results)
        self._rid = int(rid_start)
        if self._rid < 0:
            raise ValueError(f'rid_start must be >= 0, got {rid_start}')
        self._rid_start = self._rid
        if 'block_tables' not in params:
            raise NotImplementedError(
                f'{type(model).__name__} lacks block_tables in its '
                f'cached forward: paged serving needs the Llama-family '
                f'cached_attention; use DecodeEngine for this model')
        # speculative serving (docs/serving.md#speculative-serving):
        # draft != None turns every non-chunk scheduler iteration into
        # a propose/verify window — the DecodeEngine's fused
        # speculative contract (docs/decode_engine.md) composed with
        # the paged pool. The draft keeps its OWN page pools indexed
        # by the SAME block tables (page ids are bookkeeping, so one
        # allocator covers both models); greedy streams stay bit-equal
        # to the non-speculative engine, sampled streams are
        # distribution-correct (Leviathan/Chen rejection sampling).
        self.draft = draft
        self.spec_window = None
        if draft is not None:
            self.spec_window = int(num_draft_tokens)
            if self.spec_window < 1:
                raise ValueError('num_draft_tokens must be >= 1')
            dparams = inspect.signature(draft.forward).parameters
            for need in ('block_tables', 'kv_write_pos'):
                if need not in dparams:
                    raise NotImplementedError(
                        f'{type(draft).__name__} lacks {need} in its '
                        f'cached forward: the speculative draft runs '
                        f'paged single-token steps at per-row offsets')
            if 'kv_write_pos' not in params:
                raise NotImplementedError(
                    f'{type(model).__name__} lacks kv_write_pos: the '
                    f'speculative verify commits at per-row offsets')
        # kv_cache_dtype='int8' backs the slots with int8 paged pools
        # (QuantPagedKVCache: per-row scales ride with the pages, so
        # quantization is write-order independent — preemption
        # re-prefill, prefix sharing, CoW, and snapshot/restore all
        # reproduce bit-identical pages). None = the model's cache
        # dtype (prior behavior, byte for byte). 'bfloat16' keeps the
        # unquantized layout at 2-byte rows — the deployment baseline
        # the int8 migration blob's ~half-bytes headline is measured
        # against (gate_serve_disagg).
        if kv_cache_dtype is None:
            self.kv_cache_dtype = None
        else:
            kd = jnp.dtype(kv_cache_dtype)
            if kd not in (jnp.int8, jnp.bfloat16):
                raise ValueError(
                    f"kv_cache_dtype must be None, 'int8', or "
                    f"'bfloat16', got {kv_cache_dtype!r}")
            self.kv_cache_dtype = kd
        # phase-disaggregated serving (docs/serving.md#disaggregated-
        # serving): the role tags what this engine is FOR — 'prefill'
        # pools admit/chunk and hand every request off at first token
        # (disagg.PrefillEngine), 'decode' pools receive `import_kv`
        # migrations and only decode. The role changes no dispatch
        # semantics here; it keys the AOT geometry enumeration (a
        # decode pool warms import scatters, not admission prefills),
        # rides /statusz + /healthz, and lets a phase-aware router
        # place by role. 'monolithic' is prior behavior bit-for-bit.
        if phase_role not in ('monolithic', 'prefill', 'decode'):
            raise ValueError(
                f"phase_role must be 'monolithic', 'prefill', or "
                f"'decode', got {phase_role!r}")
        self.phase_role = phase_role
        if getattr(getattr(model, 'config', None), 'sliding_window',
                   None) is not None:
            raise NotImplementedError(
                'sliding-window models are not paged-servable yet: the '
                'paged kernel has no window fast path — use DecodeEngine')
        # tensor-parallel serving (docs/serving.md#tp-sharded-serving):
        # the engine owns ONE mesh whose only >1 axis is 'tp'. Device
        # state shards kv-heads over it (page pools, via
        # init_paged_cache); block tables, slot/context mirrors, and
        # every other host-fed arg upload REPLICATED; and the whole
        # host scheduler loop — admission, preemption, prefix
        # refcounts, CoW, deadlines, snapshot/restore, the journal —
        # runs on replicated host state exactly as on one chip.
        # Accepts a Mesh, a bare tp=int (serving_mesh builds the 1-D
        # mesh, virtual-device fallback included), or — when neither
        # is passed — adopts an ambient tp-only global mesh the way
        # generate() does.
        from ..distributed.mesh import get_mesh, serving_mesh

        if tp is not None and mesh is not None:
            raise ValueError(
                'pass ServingEngine(mesh=...) OR tp=..., not both')
        if tp is not None:
            tp = int(tp)
            if tp < 1:
                raise ValueError(f'tp must be >= 1, got {tp}')
            mesh = serving_mesh(tp) if tp > 1 else None
        elif mesh is None:
            amb = get_mesh()
            if (amb is not None and 'tp' in amb.axis_names
                    and amb.shape['tp'] > 1
                    and all(amb.shape[a] == 1 for a in amb.axis_names
                            if a != 'tp')):
                mesh = amb
        if mesh is not None:
            if 'tp' not in mesh.axis_names:
                raise ValueError(
                    f"ServingEngine mesh needs a 'tp' axis; got axes "
                    f'{tuple(mesh.axis_names)}')
            extra = [a for a in mesh.axis_names
                     if a != 'tp' and mesh.shape[a] > 1]
            if extra:
                raise ValueError(
                    f'ServingEngine shards over tp only; mesh axes '
                    f'{extra} have degree > 1 — run dp replicas as '
                    f'separate engines behind one queue')
            if mesh.shape['tp'] == 1:
                mesh = None          # degree 1 IS single-device serving
        self.mesh = mesh
        self.tp = int(mesh.shape['tp']) if mesh is not None else 1
        self._rep = None             # replicated NamedSharding, lazy below
        if self.mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            self._rep = NamedSharding(self.mesh, P())
            kvh = (getattr(model.config, 'num_key_value_heads', None)
                   or model.config.num_attention_heads)
            if kvh % self.tp != 0:
                import warnings

                warnings.warn(
                    f'{kvh} kv heads do not divide tp={self.tp}: the '
                    f'page pools clamp to REPLICATED (correct, but the '
                    f'KV cache no longer splits across chips) — pick a '
                    f'tp degree dividing the kv heads', stacklevel=2)
            # place the model per its declared PartitionSpecs (the
            # Llama family ships megatron column->row specs on every
            # projection; a caller that already parallelize()d gets
            # the identical placement re-applied)
            from ..distributed.parallel import shard_model

            with self._use_mesh():
                model = shard_model(model, self.mesh)
                if self.draft is not None:
                    self.draft = shard_model(self.draft, self.mesh)
        self.model = model
        self.max_slots = int(max_slots)
        self.block_size = int(block_size)
        self.max_new_tokens = int(max_new_tokens)
        self.decode_window = int(decode_window)
        if self.decode_window < 1 or self.max_slots < 1:
            raise ValueError('decode_window and max_slots must be >= 1')
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos_token_id = (int(eos_token_id) if eos_token_id is not None
                             else None)
        self.buckets = tuple(sorted(buckets)) if buckets else DEFAULT_BUCKETS
        if max_context_len is None:
            mp = getattr(getattr(model, 'config', None),
                         'max_position_embeddings', None)
            max_context_len = int(mp) if mp else 2048
        self.max_context_len = int(max_context_len)
        self.max_blocks_per_seq = _ceil_div(self.max_context_len,
                                            self.block_size)
        if num_blocks is None:
            # full coverage: every slot can hold a max-length request
            # (+1 for the reserved scratch page); pass a smaller pool to
            # actually exercise preemption
            num_blocks = self.max_slots * self.max_blocks_per_seq + 1
        self.allocator = BlockAllocator(num_blocks, self.block_size)
        if self._jr is not _journal.JOURNAL:
            self.allocator.journal = self._jr
        self.queue = RequestQueue()
        # admission control / load shedding (docs/serving.md#resilience):
        # max_queue bounds what submit() will hold (QueueFull past it —
        # preemption requeues ride above the bound, at most max_slots of
        # them); admit_watermark pauses admission while the POST-admit
        # pool utilization would exceed it, so steady traffic degrades
        # to queueing instead of preemption storms; shed_policy says
        # what a full queue does with a new arrival ('reject' it, or
        # 'evict' the lowest-priority queued request when the arrival
        # outranks it)
        self.max_queue = None if max_queue is None else int(max_queue)
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError('max_queue must be >= 1 (or None)')
        self.admit_watermark = float(admit_watermark)
        if not 0.0 < self.admit_watermark <= 1.0:
            raise ValueError(
                f'admit_watermark must be in (0, 1], got {admit_watermark}')
        if shed_policy not in ('reject', 'evict'):
            raise ValueError(
                f"shed_policy must be 'reject' or 'evict', "
                f'got {shed_policy!r}')
        self.shed_policy = shed_policy
        # prefix caching + chunked prefill (docs/serving.md#prefix):
        # prefix_cache shares full pages of identical prompt prefixes
        # across requests through the allocator's hash index (system
        # prompts amortize to ~zero prefill); prefill_chunk splits
        # long-prompt admission into <=prefill_chunk-token chunks
        # interleaved with decode windows so one long arrival never
        # stalls in-flight streams for a whole-prompt prefill. Both
        # default OFF: the monolithic admission path is bit-identical
        # to prior behavior.
        self.prefix_cache = bool(prefix_cache)
        self.prefill_chunk = (None if prefill_chunk is None
                              else int(prefill_chunk))
        if self.prefill_chunk is not None and self.prefill_chunk < 1:
            raise ValueError('prefill_chunk must be >= 1 (or None)')

        # device state, allocated ONCE (shapes never change). Under a
        # tp mesh the pools come back kv-head-sharded and the logits /
        # rng upload committed-replicated (self._put), so every later
        # dispatch sees the same input shardings the first one did.
        with self._use_mesh():
            self._pages = model.init_paged_cache(
                num_blocks, self.block_size, dtype=self.kv_cache_dtype)
            self._dpages = None
            if self.draft is not None:
                # the draft's pools share the target's page-id space:
                # same num_blocks/block_size, indexed by the same block
                # tables — one allocator, zero extra bookkeeping
                self._dpages = self.draft.init_paged_cache(
                    num_blocks, self.block_size,
                    dtype=self.kv_cache_dtype)
            vocab = model.config.vocab_size
            self._last_logits = self._put(
                jnp.zeros((self.max_slots, vocab), model.cache_dtype()))
            # sampling randomness is STATELESS per request (seed +
            # generated index fold_in chains) — the engine carries no
            # PRNG key. The draft's throwaway logits buffer feeds the
            # draft-side prefill dispatches (its per-slot scatter is
            # dropped by all-dummy slot indices; the buffer only
            # donates and comes back).
            self._dlogits = None
            self._dummy_slots = None
            if self.draft is not None:
                self._dlogits = self._put(jnp.zeros(
                    (self.max_slots, self.draft.config.vocab_size),
                    self.draft.cache_dtype()))
                # all-dummy slot indices: the draft-side prefill legs
                # drop their logits commit through the OOB scatter
                self._dummy_slots = self._put(np.full(
                    (self.max_slots,), self.max_slots, np.int32))
        # (chunk bucket, ctx bucket) shapes the draft's chunk/catch-up
        # legs have dispatched — a fresh shape's step counts as a
        # cache MISS (it paid trace + compile)
        self._draft_shapes: set = set()
        # constant all-zero forced args for the non-speculative chunk
        # path (spec_next can never be set without a draft, so the
        # per-step _forced_state scan is pure waste there)
        self._zero_ftok = self._put(np.zeros((self.max_slots,),
                                             np.int32))
        self._zero_forced = self._put(np.zeros((self.max_slots,), bool))
        # real-unit pool accounting: one page costs the sum of every
        # pool field's per-page bytes (k+v per layer; int8 pools add
        # their per-row scale rows; a draft's mirrored pools add
        # theirs) — threaded into allocator.stats() and the pool.*
        # gauges. Field shapes are the GLOBAL logical shapes even when
        # the pool is tp-sharded (each shard holds kv_heads/tp of it),
        # so the bytes_* gauges keep reporting whole-pool HBM —
        # per-shard itemsize x tp — and capacity dashboards never
        # shrink by 1/tp (tests/test_serving_tp.py pins the arithmetic)

        def _pool_page_bytes(pages):
            return int(sum(
                int(np.prod(f.shape[1:])) * f.dtype.itemsize
                for pc in pages for f in pc))

        self.allocator.bytes_per_page = (
            _pool_page_bytes(self._pages)
            + (_pool_page_bytes(self._dpages) if self._dpages else 0))

        # host-authoritative per-slot state (device copies ride in as
        # small int32/bool args each window)
        self._slot_req: list = [None] * self.max_slots
        self._slot_pages: list = [[] for _ in range(self.max_slots)]
        self._btab = np.zeros((self.max_slots, self.max_blocks_per_seq),
                              np.int32)
        self._ctx = np.zeros((self.max_slots,), np.int32)
        self._budget = np.zeros((self.max_slots,), np.int32)
        # per-slot sampling params — DATA, not statics (the traced
        # bodies take them as (SLOTS,) device args): a mixed
        # greedy/sampled/speculative workload shares one batch with
        # zero retraces as the mix changes. Mutated only at
        # place/clear, so the device copies ride the _dev mirror.
        self._temp = np.zeros((self.max_slots,), np.float32)
        self._topk = np.zeros((self.max_slots,), np.int32)
        self._topp = np.ones((self.max_slots,), np.float32)
        self._seed = np.zeros((self.max_slots,), np.uint32)
        self._plen = np.zeros((self.max_slots,), np.int32)
        # speculative engines track how much of each slot's context the
        # DRAFT's pages hold (_dctx <= _ctx): tokens committed by a
        # chunk-step's plain decode window never pass through the
        # draft, so the next speculative step first catches the draft
        # up over the hole (a _draft_chunk dispatch) — without it the
        # draft would propose against missing KV and the accept rate
        # would silently collapse
        self._dctx = np.zeros((self.max_slots,), np.int32)
        # per-slot prefill progress: None = fully prefilled (decoding);
        # an int = context tokens already in pages — the slot is mid
        # chunked/continuation prefill, rides decode windows frozen on
        # the scratch page, and emits nothing until its last chunk
        # commits. `_cow_pending` holds the (src, dst) page copy the
        # slot's first chunk dispatch must perform (prefix-cache CoW).
        self._pfill: list = [None] * self.max_slots
        self._cow_pending: list = [None] * self.max_slots
        self._cow_release: list = []     # pins freed post chunk dispatch
        # device mirror of (btab, ctx, live): rebuilt only when a slot
        # changes (admission/retire/preempt/page top-up); between those
        # the window's returned ctx is carried device-resident, so a
        # steady-state window uploads ONE small array (the budgets)
        self._dev = None

        # request registries: every submitted request is in exactly one
        # of these until its result is retrieved — `_live` (queued /
        # running / preempted) or `_terminal` (finished / failed /
        # expired / cancelled, popped by result()). `counts` are the
        # host-truth resilience counters (stats() reports them even
        # with telemetry off; the registry counters mirror them).
        # `_terminal` is bounded at `max_terminal` records (oldest
        # evicted first) so fire-and-forget cancellation or a client
        # that never collects cannot grow host memory forever; an
        # evicted rid reads as already-retrieved (KeyError).
        self.max_terminal = int(max_terminal)
        if self.max_terminal < 1:
            raise ValueError('max_terminal must be >= 1')
        self._live: dict = {}
        self._terminal: dict = {}
        # rids an active serve() batch will collect: the max_terminal
        # eviction skips these (released per-rid by result())
        self._collect_guard: set = set()
        self._deadlines_live = 0     # live requests with a deadline armed
        self.counts = {'finished': 0, 'failed': 0, 'expired': 0,
                       'cancelled': 0, 'rejected': 0, 'shed': 0,
                       'admission_paused': 0}
        self._admit_seq = itertools.count()
        self.preemption_count = 0
        self._tokens_out = 0
        self._serve_time = 0.0
        # host-truth prefix/chunk counters (stats() reports them even
        # with telemetry off; snapshot()/restore() carries them like
        # `counts` so monitoring sees no discontinuity)
        self.prefix_counts = {'hits': 0, 'misses': 0, 'hits_skipped': 0,
                              'hit_tokens': 0, 'chunked_admissions': 0,
                              'chunk_steps': 0}
        # host-truth speculative counters (stats()['spec'] reports them
        # even with telemetry off; snapshot()/restore() carries them
        # like `counts` so accept-rate dashboards see no discontinuity
        # across a failover)
        self.spec_counts = {'windows': 0, 'proposed': 0, 'accepted': 0}
        # host-truth KV-migration counters (stats()['migration'] even
        # with telemetry off; snapshot()/restore() carries them like
        # `counts`). bytes_* are blob payload bytes — what the int8
        # half-the-bf16-bytes headline is measured over.
        self.migration_counts = {'exported': 0, 'imported': 0,
                                 'import_failed': 0, 'handoffs': 0,
                                 'bytes_exported': 0, 'bytes_imported': 0}
        # telemetry hot-path caches: metric handles (refreshed when the
        # registry generation changes, i.e. after a reset) and the last
        # occupancy tuple (gauges re-set only when it moves) — keeps
        # per-step recording to a handful of attribute writes so the
        # 3% overhead gate holds even on tiny/fast models
        self._mgen = -1
        self._mx = None
        self._last_occ = None
        # cost observatory: dispatch-tag -> static flops/bytes (loaded
        # from an AOT artifact's manifest at warmup, or via
        # costs.measure_dispatch_costs). Empty = one failed dict.get
        # per step and no mfu gauges — the costless default.
        self._dispatch_costs: dict = {}
        self._peak_flops = None
        self._last_mfu = None
        # crash forensics: a propagating step() exception (the PR-8
        # worker-death path) auto-dumps a postmortem bundle here
        self.postmortem_dir = (postmortem_dir
                               or os.environ.get(
                                   'PADDLE_TPU_POSTMORTEM_DIR')
                               or None)
        self._postmortem_seq = 0
        self.last_postmortem = None
        # journal edge-trigger for pool-pressure pauses: the counter
        # ticks every paused sweep, but the rid-keyed journal event
        # fires once per STALL (a multi-hour stall must not grow the
        # held head's live — hence unevictable — trail per step)
        self._paused_head = None
        # live operability layer (docs/observability.md#slo-watchdog):
        # a windowed timeseries committed at the existing per-window
        # sync, an SLO watchdog evaluated per committed window, and an
        # opt-in ops HTTP endpoint. With none of the knobs set the
        # engine feeds the PROCESS-default ring (so `serve.tok_s` is
        # live for free) and runs no watchdog — zero new journal
        # events, prior behavior bit-identical. Any knob set gives the
        # engine a PRIVATE ring: its window BOUNDARIES and interval
        # are its own (another engine's commit cadence can't shear its
        # SLO windows), but the windowed DATA still comes from the
        # process-global registry — per-replica SLO isolation means
        # one engine per process, the dp-replica fleet shape (or a
        # custom Watchdog over a WindowedTimeseries(registry=...)).
        # `draining` flips /healthz to 503 and refuses new
        # submissions — the rolling-restart half the supervisor recipe
        # needs (drain, wait out in-flight, snapshot, close(), hand
        # off).
        self.draining = False
        self.ops_server = None
        self._watchdog = None
        # a private registry forces the private ring too: the whole
        # point of metrics_registry= is per-replica series, and the
        # windowed rate gauges ARE series (they must derive from and
        # publish into THIS replica's registry, not the process one)
        private = self._registry is not _obs.REGISTRY
        want_ops = (ops_port is not None or watchdog is not None
                    or slo_rules is not None or ts_interval_s is not None
                    or private)
        if want_ops:
            self._ts = _obs_ts.WindowedTimeseries(
                interval_s=(1.0 if ts_interval_s is None
                            else float(ts_interval_s)),
                registry=self._registry if private else None,
                journal=self._jr if private else None)
            if watchdog is not False:
                if isinstance(watchdog, _obs_wd.Watchdog):
                    self._watchdog = watchdog
                    if self._watchdog.postmortem_engine is None:
                        self._watchdog.postmortem_engine = self
                    if private and self._watchdog.registry is None:
                        self._watchdog.registry = self._registry
                    if private and self._watchdog.journal is None:
                        self._watchdog.journal = self._jr
                else:
                    rules = (slo_rules if slo_rules is not None
                             else _obs_wd.default_serving_rules(
                                 engine=self))
                    self._watchdog = _obs_wd.Watchdog(
                        rules, postmortem_engine=self,
                        registry=self._registry if private else None,
                        journal=self._jr if private else None)
        else:
            self._ts = _obs_ts.TIMESERIES
        if ops_port is not None:
            self.ops_server = _start_ops_server(
                self, port=ops_port, host=ops_host,
                registry=self._registry if private else None,
                journal=self._jr if private else None)
        self._update_gauges()

    # -- bookkeeping -------------------------------------------------------

    @contextlib.contextmanager
    def _use_mesh(self):
        """Pin the process-global mesh to THIS engine's mesh (None
        included) for a dispatch: the traced bodies' sharding pins and
        the model's own `sharding_constraint`s read `get_mesh()` at
        trace time, so every trace — warmup or live — must see exactly
        the engine's mesh regardless of ambient state. Identity-check
        fast path: steady-state steps under an already-matching (or
        absent) global mesh pay two attribute reads."""
        from ..distributed import mesh as _mesh_mod

        prev = _mesh_mod.get_mesh()
        if prev is self.mesh:
            yield
            return
        _mesh_mod.set_mesh(self.mesh)
        try:
            yield
        finally:
            _mesh_mod.set_mesh(prev)

    def _put(self, x):
        """Upload one host-fed dispatch arg. Unsharded engines:
        `jnp.asarray` (prior behavior, byte for byte). TP engines:
        committed-REPLICATED over the mesh — mixing committed sharded
        pools with uncommitted single-device mirrors would give the
        first and second dispatch of a geometry different input
        shardings (one retrace each), and warm-attach zero-compile
        plus the steady-state zero-retrace contract both need the key
        stable from call one."""
        if self._rep is None:
            return jnp.asarray(x)
        return jax.device_put(x, self._rep)

    def _sampling_key(self):
        return (self.max_new_tokens, self.temperature, self.top_k,
                self.top_p, self.eos_token_id)

    def _geometry(self):
        # tp is part of the geometry: a tp=1 and a tp=2 engine over
        # the same pool shape dispatch DIFFERENT executables (jax keys
        # them by input sharding), so the CompileCache registry must
        # not let their notes collide either. A speculative engine
        # additionally folds in its draft's identity + window: two
        # engines over the same target but different drafts trace
        # different programs.
        g = ('paged', self.max_slots, self.allocator.num_blocks,
             self.block_size, self.max_blocks_per_seq, self.tp)
        if self.draft is not None:
            from .engine import model_tag

            g = g + ('spec', self.spec_window, model_tag(self.draft))
        return g

    def registry_key(self, *tag):
        """The EXACT CompileCache key `_note(*tag)` records (the shared
        recipe: pool shape + POOL dtype + sampling config + `tag` +
        geometry). Tags are the dispatch kinds step() uses:
        ('serve_step', W, Sb), ('serve_window', W),
        ('serve_prefill', Sb), ('serve_chunk_step', W, Cb, Sb),
        ('serve_spec_step', k, Sb, Cx), ('serve_spec_window', k, Cx),
        plus the migration pair export_kv/import_kv dispatch:
        ('serve_export', Cx), ('serve_import', Cx).
        The pool dtype (int8 vs the model's cache dtype) keys here, so
        a quantized and an unquantized engine over one model never
        collide. Exposed so aot.GeometrySet enumeration and the live
        engine provably agree key-for-key."""
        return COMPILE_CACHE.key(
            self.model, self._pages[0].kp.shape,
            self._pages[0].kp.dtype,
            self._sampling_key() + tag, geometry=self._geometry())

    def _note(self, *tag):
        """Record one engine-level registry key. Returns the registry
        verdict — True on hit, False when the key is NEW (this dispatch
        pays trace + compile; step() turns that into a compile span
        with the measured wall duration)."""
        return COMPILE_CACHE.note(self.registry_key(*tag))

    # scoped-telemetry writers: a private-registry replica's counters/
    # gauges land in ITS registry (the fleet's per-replica signals),
    # a default engine hits the module conveniences byte-for-byte.
    # compile.* stays global on purpose — engine.py's trace counters
    # are process truth either way.
    def _inc(self, name, n=1):
        if self._registry is _obs.REGISTRY:
            _obs.inc(name, n)
        elif _obs.enabled():
            self._registry.counter(name).inc(n)

    def _set_gauge(self, name, v):
        if self._registry is _obs.REGISTRY:
            _obs.set_gauge(name, v)
        elif _obs.enabled():
            self._registry.gauge(name).set(v)

    def _record(self, kind, **fields):
        self._jr.record(kind, **fields)

    def _metrics(self):
        """Cached registry handles for the hot per-step records (the
        generation check makes a registry reset() safe: stale handles
        are re-resolved instead of written into orphaned objects)."""
        R = self._registry
        if self._mgen != R.generation:
            self._mx = {
                'ttft': R.histogram('serve.ttft_ms'),
                'itl': R.histogram('serve.itl_ms'),
                'qwait': R.histogram('serve.queue_wait_ms'),
                'step_ms': R.histogram('serve.step_ms'),
                'steps': R.counter('serve.steps'),
                'tokens': R.counter('serve.tokens'),
                'in_flight': R.gauge('serve.in_flight'),
                'queue_depth': R.gauge('serve.queue_depth'),
                'pages_in_use': R.gauge('pool.pages_in_use'),
                'util': R.gauge('pool.utilization'),
                'bytes_in_use': R.gauge('pool.bytes_in_use'),
                'bytes_total': R.gauge('pool.bytes_total'),
                'pressure': R.gauge('serve.pool_pressure'),
                'pfx_shared': R.gauge('pool.prefix_shared_pages'),
                'pfx_cached': R.gauge('pool.prefix_cached_pages'),
                'pfx_cow': R.gauge('pool.prefix_cow_pages'),
                'pfx_shared_b': R.gauge('pool.prefix_shared_bytes'),
                'pfx_cached_b': R.gauge('pool.prefix_cached_bytes'),
                'migration_ms': R.histogram('serve.migration_ms'),
            }
            self._mgen = R.generation
            self._last_occ = None          # force a gauge refresh
        return self._mx

    def _update_gauges(self):
        """Occupancy/pool gauges, refreshed at the step boundary only
        when occupancy actually moved (host bookkeeping only; a steady
        full batch skips all six writes)."""
        if not _obs.enabled():
            return
        m = self._metrics()
        a = self.allocator
        occ = (self.in_flight(), len(self.queue), a.in_use(),
               a.cached(), a.shared(), a.cow_count)
        if occ == self._last_occ:
            return
        self._last_occ = occ
        m['in_flight'].set(occ[0])
        m['queue_depth'].set(occ[1])
        m['pages_in_use'].set(occ[2])
        m['util'].set(a.utilization())
        # watermark-relative pool pressure: 1.0 == AT the admission
        # watermark (>= 1.0 means admission is pausing)
        m['pressure'].set(a.utilization() / self.admit_watermark)
        m['pfx_cached'].set(occ[3])
        m['pfx_shared'].set(occ[4])
        m['pfx_cow'].set(occ[5])
        if a.bytes_per_page:
            m['bytes_in_use'].set(occ[2] * a.bytes_per_page)
            m['bytes_total'].set(a.num_blocks * a.bytes_per_page)
            m['pfx_cached_b'].set(occ[3] * a.bytes_per_page)
            m['pfx_shared_b'].set(occ[4] * a.bytes_per_page)

    def in_flight(self):
        return sum(r is not None for r in self._slot_req)

    def stats(self):
        """Serving observability: throughput, occupancy, pool
        utilization, scheduling counters, and the shared retrace
        counters (steady-state serving must hold total_traces flat —
        bench.py's gate_serve_retrace_zero asserts it)."""
        return {
            'trace_counts': trace_counts(),
            'total_traces': total_traces(),
            'tokens_generated': self._tokens_out,
            'tokens_per_s': (self._tokens_out / self._serve_time
                             if self._serve_time > 0 else 0.0),
            'in_flight': self.in_flight(),
            'queue_depth': len(self.queue),
            'preemptions': self.preemption_count,
            'resilience': {'max_queue': self.max_queue,
                           'admit_watermark': self.admit_watermark,
                           'shed_policy': self.shed_policy,
                           **self.counts},
            'prefix': {'enabled': self.prefix_cache,
                       'prefill_chunk': self.prefill_chunk,
                       **self.prefix_counts,
                       **self.allocator.stats()['prefix']},
            # host-truth speculative record: accept_rate is accepted
            # draft tokens over proposed (None before the first window)
            'spec': {'enabled': self.draft is not None,
                     'num_draft_tokens': self.spec_window,
                     'kv_cache_dtype': (str(self.kv_cache_dtype)
                                        if self.kv_cache_dtype else None),
                     **self.spec_counts,
                     'accept_rate': (
                         self.spec_counts['accepted']
                         / self.spec_counts['proposed']
                         if self.spec_counts['proposed'] else None)},
            # host-truth MFU record of the last all-hit window (tag,
            # static flops, wall) — what gate_flight_recorder checks
            # the serve.mfu_est gauge and the AOT manifest against
            'mfu': self._last_mfu,
            # host-truth health verdict (None when no watchdog is
            # configured) + drain state — what /statusz and a
            # supervisor poll without parsing /healthz
            'watchdog': (self._watchdog.verdict()
                         if self._watchdog is not None else None),
            'draining': self.draining,
            # disaggregated serving: which phase this engine runs, and
            # the host-truth migration record (export/import/handoff
            # counts + blob bytes moved)
            'phase_role': self.phase_role,
            'migration': dict(self.migration_counts),
            'blocks': self.allocator.stats(),
            'geometry': {'kind': 'paged', 'max_slots': self.max_slots,
                         'block_size': self.block_size,
                         'num_blocks': self.allocator.num_blocks,
                         'max_blocks_per_seq': self.max_blocks_per_seq,
                         'decode_window': self.decode_window,
                         'tp': self.tp},
        }

    # -- AOT artifact hooks (paddle_tpu.aot) -------------------------------

    def aot_config(self):
        """Compilation-relevant config as a dict of primitives (what
        two engines must share for one EngineArtifact to serve both;
        weights are structure, not values — see DecodeEngine)."""
        from .engine import model_struct, model_tag

        return {
            'engine': 'ServingEngine',
            'model': model_tag(self.model),
            'model_struct': model_struct(self.model),
            'cache_dtype': str(self.model.cache_dtype()),
            'max_slots': self.max_slots,
            'block_size': self.block_size,
            'num_blocks': self.allocator.num_blocks,
            'max_context_len': self.max_context_len,
            'max_new_tokens': self.max_new_tokens,
            'decode_window': self.decode_window,
            'temperature': self.temperature,
            'top_k': self.top_k,
            'top_p': self.top_p,
            'eos_token_id': self.eos_token_id,
            'buckets': list(self.buckets),
            'prefix_cache': self.prefix_cache,
            'prefill_chunk': self.prefill_chunk,
            # speculative + quantized serving are compilation-relevant:
            # a spec artifact's executables close over the draft's
            # structure, an int8 artifact's over the pool dtype —
            # attaching across either must refuse (ArtifactMismatch
            # names the field)
            'kv_cache_dtype': (str(self.kv_cache_dtype)
                               if self.kv_cache_dtype else None),
            'num_draft_tokens': self.spec_window,
            'draft': (model_tag(self.draft) if self.draft is not None
                      else None),
            'draft_struct': (model_struct(self.draft)
                             if self.draft is not None else None),
            # the mesh degree is compilation-relevant: a tp=4
            # artifact's executables are 4-shard SPMD programs a tp=1
            # engine can never look up — attaching across degrees must
            # refuse (ArtifactMismatch names this field)
            'tp': self.tp,
        }

    def _aot_jitted_fns(self):
        """The module-level jitted steps this engine's geometries
        dispatch — what `aot.build` cache-evicts (per FUNCTION, not
        process-wide) to force real persisting compiles."""
        return (_paged_prefill, _serve_window, _serve_step,
                _serve_chunk_step, _serve_spec_window, _serve_spec_step,
                _draft_chunk, _kv_export, _kv_import)

    def _warm_geometry(self, g, draft=None):
        """Drive ONE enumerated geometry through the SAME module-level
        jitted steps the scheduler dispatches, with an all-dummy slot
        batch: real_len 0 rows land on the scratch page, slot indices
        max_slots drop their logits on the OOB scatter, and live=False
        freezes every row — so warming an IDLE engine (enforced below)
        mutates no scheduler state beyond the (donated, re-assigned)
        device pools. The args come from the same builders step() uses
        (`_prefill_args`, `_device_state`), so the traced avals are the
        live ones by construction."""
        p = g.params
        W = self.decode_window
        if p.get('window', W) != W:
            raise ValueError(
                f'geometry {g.label()} was enumerated for decode_window '
                f"{p['window']}, engine has {W}")
        if self.in_flight():
            # the dummy batch is only inert when every slot is empty: a
            # LIVE row would really decode through the dummy window
            # (pages written, last_logits advanced) while the host
            # mirror commits nothing — silent token corruption for
            # every in-flight request
            raise RuntimeError(
                f'cannot warm a ServingEngine with {self.in_flight()} '
                f'request(s) in flight: drain the batch (run()) before '
                f'warmup/aot.build')
        with self._use_mesh():
            dev = self._device_state()
            budget = self._put(self._budget)
            common = dict(window=W, eos_token_id=self.eos_token_id)
            sample_args = (dev['temp'], dev['topk'], dev['topp'],
                           dev['seed'], dev['plen'])
            K = self.max_slots
            if g.kind == 'serve_step':
                ids, real_len, btabs, slots = self._prefill_args(
                    p['bucket'], [])
                self._note('serve_step', W, p['bucket'])
                _, self._last_logits, self._pages, _ = _serve_step(
                    self.model, self._pages, self._last_logits, ids,
                    real_len, btabs, slots, dev['btab'], dev['ctx'],
                    dev['live'], budget, *sample_args, **common)
            elif g.kind == 'serve_window':
                self._note('serve_window', W)
                _, self._last_logits, self._pages, _ = _serve_window(
                    self.model, self._pages, self._last_logits,
                    dev['btab'], dev['ctx'], dev['live'], budget,
                    *sample_args, **common)
            elif g.kind == 'serve_prefill':
                ids, real_len, btabs, slots = self._prefill_args(
                    p['bucket'], [])
                self._note('serve_prefill', p['bucket'])
                self._last_logits, self._pages = _paged_prefill(
                    self.model, self._pages, self._last_logits, ids,
                    real_len, btabs, slots)
                if self.draft is not None:
                    # the live standalone prefill runs a draft leg too
                    self._dlogits, self._dpages = _paged_prefill(
                        self.draft, self._dpages, self._dlogits, ids,
                        real_len, btabs, self._dummy_slots)
            elif g.kind == 'serve_chunk_step':
                Cb, Sb = int(p['chunk']), int(p['bucket'])
                ids = self._put(np.zeros((K, Cb), np.int32))
                z = self._put(np.zeros((K,), np.int32))
                btabs = self._put(
                    np.zeros((K, self.max_blocks_per_seq), np.int32))
                slots = self._put(
                    np.full((K,), K, np.int32))   # all dummies: drop
                self._note('serve_chunk_step', W, Cb, Sb)
                zb = self._put(np.zeros((K,), bool))
                if self.draft is not None:
                    # the live chunk step runs a draft leg too
                    self._draft_shapes.add((Cb, Sb))
                    self._dlogits, self._dpages = _draft_chunk(
                        self.draft, self._dpages, self._dlogits, ids,
                        z, z, btabs, slots, z, z, ctx_bucket=Sb)
                    self._warm_draft_catchup(Sb, z, btabs)
                _, self._last_logits, self._pages, _ = _serve_chunk_step(
                    self.model, self._pages, self._last_logits, ids, z,
                    z, btabs, slots, z, z, dev['btab'], dev['ctx'],
                    dev['live'], budget, *sample_args, z, zb,
                    ctx_bucket=Sb, **common)
            elif g.kind in ('serve_spec_step', 'serve_spec_window'):
                if self.draft is None:
                    raise ValueError(
                        f'geometry {g.label()} needs a speculative '
                        f'engine (construct with draft=...)')
                k = int(p['spec'])
                if k != self.spec_window:
                    raise ValueError(
                        f'geometry {g.label()} was enumerated for '
                        f'num_draft_tokens {k}, engine has '
                        f'{self.spec_window}')
                Cx = int(p['ctx'])
                z = self._put(np.zeros((K,), np.int32))
                forced = self._put(np.zeros((K,), bool))
                scommon = dict(k=k, ctx_bucket=Cx,
                               eos_token_id=self.eos_token_id)
                if (self.prefill_chunk is not None or self.prefix_cache
                        or self.phase_role == 'decode'):
                    # chunk steps can commit window tokens past the
                    # draft; the catch-up `_draft_chunk` shapes a live
                    # spec step can then dispatch (hole bucket x THIS
                    # geometry's ctx bucket) must be warm too, or a
                    # warm-attached engine would compile mid-serve
                    # (decode-role pools re-enter through the one-token
                    # continuation chunk, which opens the same hole)
                    self._warm_draft_catchup(
                        Cx, z,
                        self._put(np.zeros(
                            (K, self.max_blocks_per_seq), np.int32)))
                if g.kind == 'serve_spec_step':
                    ids, real_len, btabs, slots = self._prefill_args(
                        p['bucket'], [])
                    self._note('serve_spec_step', k, p['bucket'], Cx)
                    (_, _, _, self._last_logits, self._pages,
                     self._dpages, _) = _serve_spec_step(
                        self.model, self.draft, self._pages,
                        self._dpages, self._last_logits, ids, real_len,
                        btabs, slots, z, forced, dev['btab'],
                        dev['ctx'], dev['live'], budget, *sample_args,
                        **scommon)
                else:
                    self._note('serve_spec_window', k, Cx)
                    (_, _, _, self._last_logits, self._pages,
                     self._dpages, _) = _serve_spec_window(
                        self.model, self.draft, self._pages,
                        self._dpages, self._last_logits, z, forced,
                        dev['btab'], dev['ctx'], dev['live'], budget,
                        *sample_args, **scommon)
            elif g.kind == 'serve_export':
                # the migration gather at K=1: a zero start length
                # reads only the scratch page, so warming is inert
                # beyond the jit cache (no donation — pools untouched)
                Cx = int(p['ctx'])
                self._note('serve_export', Cx)
                btabs1 = self._put(
                    np.zeros((1, self.max_blocks_per_seq), np.int32))
                st1 = self._put(np.zeros((1,), np.int32))
                _kv_export(self._pages, btabs1, st1, ctx_bucket=Cx)
                if self.draft is not None:
                    # the live export ships the draft's pages too
                    _kv_export(self._dpages, btabs1, st1, ctx_bucket=Cx)
            elif g.kind == 'serve_import':
                # the migration scatter: all-zero targets write only
                # the reserved scratch page (donated pools come back
                # re-assigned, nothing live is touched). The zero blob
                # rides the SAME `_blob_device_entries` upload the live
                # import uses, so the warmed avals are the live ones
                # by construction.
                Cx = int(p['ctx'])
                self._note('serve_import', Cx)
                zi = self._put(np.zeros((Cx,), np.int32))
                ents = self._blob_device_entries(self._pages, Cx)
                self._pages = _kv_import(self._pages, ents, zi, zi,
                                         ctx_bucket=Cx)
                if self.draft is not None:
                    dents = self._blob_device_entries(self._dpages, Cx)
                    self._dpages = _kv_import(self._dpages, dents, zi,
                                              zi, ctx_bucket=Cx)
            else:
                raise ValueError(
                    f'unknown serving geometry kind {g.kind!r}')

    def _warm_draft_catchup(self, Sb, z, btabs):
        """Warm the draft catch-up `_draft_chunk` shapes reachable at
        context bucket `Sb`: holes are bounded by one decode window
        per step, so their chunk buckets are the ladder entries at or
        below bucket(decode_window)."""
        K = self.max_slots
        cbs, v = [], 1
        while v <= self.decode_window:
            b = bucket_length(v, self.buckets)
            cbs.append(b)
            v = b + 1
        for cb in cbs:
            if (cb, Sb) in self._draft_shapes:
                continue
            self._draft_shapes.add((cb, Sb))
            ids = self._put(np.zeros((K, cb), np.int32))
            self._dlogits, self._dpages = _draft_chunk(
                self.draft, self._dpages, self._dlogits, ids, z, z,
                btabs, self._dummy_slots, z, z, ctx_bucket=Sb)

    def warmup(self, artifact=None, geometries=None, draft=None):
        """Pre-populate the module-level jit caches (and the
        CompileCache registry) for every geometry this engine's config
        implies, BEFORE the first request — with an `aot.EngineArtifact`
        the compiles are persistent-cache disk reads, so a fresh
        replica's first request is ZERO compiles. Returns a report
        dict; see docs/aot_warmup.md."""
        from ..aot.artifact import warm_attach

        return warm_attach(self, artifact=artifact, geometries=geometries,
                           draft=draft)

    def _export_specs(self, g, draft=None):
        """(suffix, jitted_fn, args) for `aot.build(...,
        export_stablehlo=True)`. The model is closed over (the jit.save
        idiom — a Layer in the calling convention would refuse to
        serialize); the page pools stay ARGS, as ShapeDtypeStruct avals
        of the engine's live pools (they are state, not weights — the
        exported module must take them, and PagedKVCache is a
        registered serializable container)."""
        p = g.params
        W = self.decode_window
        K = self.max_slots

        def sds(x):
            return jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), x)

        pages = sds(self._pages)
        logits = sds(self._last_logits)
        btab = jax.ShapeDtypeStruct((K, self.max_blocks_per_seq),
                                    jnp.int32)
        ctx = jax.ShapeDtypeStruct((K,), jnp.int32)
        live = jax.ShapeDtypeStruct((K,), jnp.bool_)
        budget = jax.ShapeDtypeStruct((K,), jnp.int32)
        fvec = jax.ShapeDtypeStruct((K,), jnp.float32)
        svec = jax.ShapeDtypeStruct((K,), jnp.uint32)
        ivec = jax.ShapeDtypeStruct((K,), jnp.int32)
        samp = (fvec, ivec, fvec, svec, ivec)   # temp/topk/topp/seed/plen
        common = dict(window=W, eos_token_id=self.eos_token_id)
        if g.kind in ('serve_step', 'serve_prefill', 'serve_spec_step'):
            ids = jax.ShapeDtypeStruct((K, int(p['bucket'])), jnp.int32)
            rl = jax.ShapeDtypeStruct((K,), jnp.int32)
            btabs = jax.ShapeDtypeStruct((K, self.max_blocks_per_seq),
                                         jnp.int32)
            slots = jax.ShapeDtypeStruct((K,), jnp.int32)
        elif g.kind == 'serve_chunk_step':
            ids = jax.ShapeDtypeStruct((K, int(p['chunk'])), jnp.int32)
            rl = jax.ShapeDtypeStruct((K,), jnp.int32)
            btabs = jax.ShapeDtypeStruct((K, self.max_blocks_per_seq),
                                         jnp.int32)
            slots = jax.ShapeDtypeStruct((K,), jnp.int32)

        def wrap(base, *extra_models, **statics):
            # tracelint: disable=TL001 - one-shot export wrapper (model
            # and statics baked into the closure; never a hot path)
            return jax.jit(functools.partial(
                getattr(base, '__wrapped__', base), self.model,
                *extra_models, **statics))

        if g.kind == 'serve_step':
            yield ('', wrap(_serve_step, **common),
                   (pages, logits, ids, rl, btabs, slots, btab, ctx,
                    live, budget) + samp)
        elif g.kind == 'serve_window':
            yield ('', wrap(_serve_window, **common),
                   (pages, logits, btab, ctx, live, budget) + samp)
        elif g.kind == 'serve_prefill':
            yield ('', wrap(_paged_prefill),
                   (pages, logits, ids, rl, btabs, slots))
        elif g.kind == 'serve_chunk_step':
            fbool = jax.ShapeDtypeStruct((K,), jnp.bool_)
            yield ('', wrap(_serve_chunk_step,
                            ctx_bucket=int(p['bucket']), **common),
                   (pages, logits, ids, rl, rl, btabs, slots, rl, rl,
                    btab, ctx, live, budget) + samp + (ivec, fbool))
        elif g.kind == 'serve_spec_step':
            dpages = sds(self._dpages)
            fbool = jax.ShapeDtypeStruct((K,), jnp.bool_)
            yield ('', wrap(_serve_spec_step, self.draft,
                            k=int(p['spec']), ctx_bucket=int(p['ctx']),
                            eos_token_id=self.eos_token_id),
                   (pages, dpages, logits, ids, rl, btabs, slots, ivec,
                    fbool, btab, ctx, live, budget) + samp)
        elif g.kind == 'serve_spec_window':
            dpages = sds(self._dpages)
            fbool = jax.ShapeDtypeStruct((K,), jnp.bool_)
            yield ('', wrap(_serve_spec_window, self.draft,
                            k=int(p['spec']), ctx_bucket=int(p['ctx']),
                            eos_token_id=self.eos_token_id),
                   (pages, dpages, logits, ivec, fbool, btab, ctx,
                    live, budget) + samp)
        else:
            raise NotImplementedError(
                f'no StableHLO export for geometry kind {g.kind!r}')

    def _cost_specs(self, g, draft=None):
        """(jitted_fn, args, static_kwargs) triples for
        `observability.costs.geometry_cost`: the SAME module-level
        jitted steps the scheduler dispatches, over ShapeDtypeStruct
        avals with the live model as the first argument — so the
        lowered HLO (and its cost analysis) is exactly the served
        executable's, not a weights-as-constants export variant."""
        p = g.params
        W = self.decode_window
        K = self.max_slots

        def sds(x):
            return jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), x)

        pages = sds(self._pages)
        logits = sds(self._last_logits)
        btab = jax.ShapeDtypeStruct((K, self.max_blocks_per_seq),
                                    jnp.int32)
        vec = jax.ShapeDtypeStruct((K,), jnp.int32)
        live = jax.ShapeDtypeStruct((K,), jnp.bool_)
        fvec = jax.ShapeDtypeStruct((K,), jnp.float32)
        svec = jax.ShapeDtypeStruct((K,), jnp.uint32)
        samp = (fvec, vec, fvec, svec, vec)
        common = dict(window=W, eos_token_id=self.eos_token_id)
        if g.kind == 'serve_step':
            ids = jax.ShapeDtypeStruct((K, int(p['bucket'])), jnp.int32)
            btabs = jax.ShapeDtypeStruct((K, self.max_blocks_per_seq),
                                         jnp.int32)
            yield (_serve_step,
                   (self.model, pages, logits, ids, vec, btabs, vec,
                    btab, vec, live, vec) + samp, common)
        elif g.kind == 'serve_window':
            yield (_serve_window,
                   (self.model, pages, logits, btab, vec, live, vec)
                   + samp, common)
        elif g.kind == 'serve_prefill':
            ids = jax.ShapeDtypeStruct((K, int(p['bucket'])), jnp.int32)
            btabs = jax.ShapeDtypeStruct((K, self.max_blocks_per_seq),
                                         jnp.int32)
            yield (_paged_prefill,
                   (self.model, pages, logits, ids, vec, btabs, vec), {})
        elif g.kind == 'serve_chunk_step':
            ids = jax.ShapeDtypeStruct((K, int(p['chunk'])), jnp.int32)
            btabs = jax.ShapeDtypeStruct((K, self.max_blocks_per_seq),
                                         jnp.int32)
            fbool = jax.ShapeDtypeStruct((K,), jnp.bool_)
            yield (_serve_chunk_step,
                   (self.model, pages, logits, ids, vec, vec, btabs,
                    vec, vec, vec, btab, vec, live, vec) + samp
                   + (vec, fbool),
                   dict(ctx_bucket=int(p['bucket']), **common))
        elif g.kind == 'serve_spec_step':
            dpages = sds(self._dpages)
            ids = jax.ShapeDtypeStruct((K, int(p['bucket'])), jnp.int32)
            btabs = jax.ShapeDtypeStruct((K, self.max_blocks_per_seq),
                                         jnp.int32)
            fbool = jax.ShapeDtypeStruct((K,), jnp.bool_)
            yield (_serve_spec_step,
                   (self.model, self.draft, pages, dpages, logits, ids,
                    vec, btabs, vec, vec, fbool, btab, vec, live, vec)
                   + samp,
                   dict(k=int(p['spec']), ctx_bucket=int(p['ctx']),
                        eos_token_id=self.eos_token_id))
        elif g.kind == 'serve_spec_window':
            dpages = sds(self._dpages)
            fbool = jax.ShapeDtypeStruct((K,), jnp.bool_)
            yield (_serve_spec_window,
                   (self.model, self.draft, pages, dpages, logits, vec,
                    fbool, btab, vec, live, vec) + samp,
                   dict(k=int(p['spec']), ctx_bucket=int(p['ctx']),
                        eos_token_id=self.eos_token_id))
        elif g.kind == 'serve_export':
            btabs1 = jax.ShapeDtypeStruct((1, self.max_blocks_per_seq),
                                          jnp.int32)
            st = jax.ShapeDtypeStruct((1,), jnp.int32)
            yield (_kv_export, (pages, btabs1, st),
                   dict(ctx_bucket=int(p['ctx'])))
        elif g.kind == 'serve_import':
            Cx = int(p['ctx'])
            blob = sds(self._blob_aval_entries(Cx))
            pflat = jax.ShapeDtypeStruct((Cx,), jnp.int32)
            yield (_kv_import, (pages, blob, pflat, pflat),
                   dict(ctx_bucket=Cx))
        else:
            raise NotImplementedError(
                f'no cost specs for geometry kind {g.kind!r}')

    def _blob_aval_entries(self, Cx):
        """Zero-filled `_blob_device_entries` payload at the `Cx`
        bucket — the aval source for `serve_export`/`serve_import`
        cost/lint specs, so the analyzed scatter is shape-identical to
        the live `import_kv` dispatch by construction."""
        return self._blob_device_entries(self._pages, Cx)

    def _geometry_cost_tag(self, g):
        """The dispatch tag `step()` keys its registry notes with, for
        one enumerated geometry — the join key between the manifest's
        per-geometry costs and the live window-commit MFU math."""
        p = g.params
        W = int(p.get('window', self.decode_window))
        if g.kind == 'serve_step':
            return ('serve_step', W, int(p['bucket']))
        if g.kind == 'serve_window':
            return ('serve_window', W)
        if g.kind == 'serve_prefill':
            return ('serve_prefill', int(p['bucket']))
        if g.kind == 'serve_chunk_step':
            return ('serve_chunk_step', W, int(p['chunk']),
                    int(p['bucket']))
        if g.kind == 'serve_spec_step':
            return ('serve_spec_step', int(p['spec']), int(p['bucket']),
                    int(p['ctx']))
        if g.kind == 'serve_spec_window':
            return ('serve_spec_window', int(p['spec']), int(p['ctx']))
        return None

    def _note_geometry_cost(self, g, cost):
        """Bind one geometry's static flops/bytes (an aot manifest's
        `cost` entry, or costs.geometry_cost output) to its dispatch
        tag. From then on every all-hit window commit derives
        `serve.mfu_est` / roofline gauges from host data alone — the
        static flops and the wall clock the commit already reads."""
        tag = self._geometry_cost_tag(g)
        if tag is None or not isinstance(cost, dict) \
                or not cost.get('flops'):
            return
        self._dispatch_costs[tag] = cost
        if self._peak_flops is None:
            from ..observability import costs as _costs

            self._peak_flops = _costs.device_peak_flops()

    # -- public API --------------------------------------------------------

    def submit(self, prompt, max_new_tokens=None, priority=0,
               deadline_s=None, temperature=None, top_k=None,
               top_p=None, seed=None):
        """Queue one request; returns its id for `result()`. Validated
        against the pool so an undeliverable request fails HERE, not as
        a livelock mid-serve. `deadline_s` (seconds from now) bounds
        total latency: a request still unfinished past it transitions
        to state 'expired' at the next window commit (or at admission,
        if it expires while queued). Raises `QueueFull` when the queue
        is at `max_queue` and the shed policy keeps the newcomer out —
        the caller's backpressure signal.

        `temperature`/`top_k`/`top_p`/`seed` are PER-REQUEST sampling
        params (default: the engine's construction-time config; seed
        defaults to the rid). They ride as slot data, so any mix of
        greedy and sampled requests shares one batch with zero
        retraces. Validated HERE with a typed `InvalidSamplingParams`
        BEFORE the prompt copy is paid: temperature < 0 and
        top_p outside (0, 1] reject; top_k clamps to the vocab (the
        `filter_logits` HF semantics — top_k > V means keep-all,
        top_k <= 0 disables the filter)."""
        temperature = (self.temperature if temperature is None
                       else float(temperature))
        if temperature < 0:
            raise InvalidSamplingParams(
                f'temperature must be >= 0 (0 = greedy), got '
                f'{temperature}')
        top_p = self.top_p if top_p is None else float(top_p)
        if not 0.0 < top_p <= 1.0:
            raise InvalidSamplingParams(
                f'top_p must be in (0, 1], got {top_p}')
        top_k = self.top_k if top_k is None else int(top_k)
        top_k = max(0, min(top_k, int(self.model.config.vocab_size)))
        if self.draining:
            # drain is admission control, not validation: refuse with
            # the same typed backpressure signal a full queue gives,
            # counted under 'rejected' so the refusals are visible
            self.counts['rejected'] += 1
            self._inc('serve.rejected')
            raise QueueFull(
                'engine draining: new submissions refused — route to '
                'another replica (drain(False) reopens admission)')
        mnt = (self.max_new_tokens if max_new_tokens is None
               else int(max_new_tokens))
        if mnt < 1:
            raise ValueError('max_new_tokens must be >= 1')
        if deadline_s is not None and float(deadline_s) <= 0:
            raise ValueError('deadline_s must be > 0 (seconds from now)')
        # coerced HERE so the shed decision ranks the newcomer exactly
        # as Request will store it (a fractional 0.5 must not outrank
        # the priority-0 peer it would be stored equal to)
        priority = int(priority)
        # validation and the queue-bound verdict both read the token
        # COUNT alone: a rejected submit is the designed high-frequency
        # backpressure path, so it must not pay the Request's prompt
        # copy just to throw it away. np.size is O(1) on an ndarray and
        # counts the flattened length Request.__init__ will reshape to,
        # so multi-dimensional prompts can't sneak past the fit guards
        plen = int(np.size(prompt))
        if plen == 0:
            raise ValueError('empty prompt')
        total = plen + mnt
        if total > self.max_context_len:
            raise ValueError(
                f'prompt + max_new_tokens = {total} exceeds '
                f'max_context_len {self.max_context_len}')
        if _ceil_div(total, self.block_size) > self.allocator.usable:
            raise ValueError(
                f'request needs {_ceil_div(total, self.block_size)} '
                f'pages but the pool only has {self.allocator.usable} '
                f'usable — grow num_blocks')
        victim = None
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            # never shed live traffic to protect dead work: entries
            # whose deadline already passed while queued are retired
            # here (they'd be swept at admission anyway) before the
            # bound is judged
            self._sweep_expired_queue()
            if len(self.queue) >= self.max_queue:
                victim = self._shed_for(priority)  # raises QueueFull
                                                   # unless it can evict
        # the victim is only PICKED above — it is evicted after Request
        # construction succeeds, so a malformed prompt that np.asarray
        # rejects cannot cancel an innocent queued request on its way
        # to raising
        req = Request(self._rid, prompt, mnt, priority,
                      temperature=temperature, top_k=top_k, top_p=top_p,
                      sample_seed=(self._rid if seed is None
                                   else int(seed)))
        if self._jr is not _journal.JOURNAL:
            req.journal = self._jr
        if victim is not None:
            self._shed(victim)
        self._rid += 1
        if deadline_s is not None:
            req.deadline = time.perf_counter() + float(deadline_s)
            self._deadlines_live += 1
        req.mark('arrival', prompt_len=plen, max_new_tokens=mnt,
                 priority=priority)
        self._inc('serve.requests')
        self._live[req.rid] = req
        self.queue.push(req)
        return req.rid

    def _sweep_expired_queue(self):
        """Retire every queued or preempted request whose deadline has
        already passed — called when the queue bound is hit, so a
        full-of-dead-work queue never rejects live traffic (deadline
        death is not shedding: even a preempted request's generated
        work is worthless once nobody is waiting for it). Early-outs
        without scanning when no live request has a deadline armed —
        the common config on the reject hot path."""
        if not self._deadlines_live:
            return
        now = time.perf_counter()
        for r in [r for r in self.queue.live()
                  if r.deadline is not None and now >= r.deadline]:
            self.queue.remove(r)
            self._retire(r, 'expired',
                         reason='deadline exceeded while queued')

    def _shed_for(self, priority):
        """The queue is full: under 'evict', pick the lowest-priority
        (then youngest-arrival) QUEUED request for displacement if the
        newcomer at `priority` outranks it — preempted requests are
        never shed (they hold generated work). Otherwise reject the
        newcomer. Deterministic either way; returns the victim (the
        caller evicts via `_shed` once the newcomer is actually
        admissible) or raises QueueFull."""
        victim = None
        if self.shed_policy == 'evict':
            queued = [r for r in self.queue.live() if r.state == 'queued']
            if queued:
                cand = min(queued, key=lambda r: (r.priority, -r.seq))
                if cand.priority < priority:
                    victim = cand
        if victim is None:
            self.counts['rejected'] += 1
            self._inc('serve.rejected')
            raise QueueFull(
                f'queue full ({len(self.queue)}/{self.max_queue}), '
                f'policy={self.shed_policy!r}: request rejected — back '
                f'off and resubmit')
        return victim

    def _shed(self, victim):
        """Evict a `_shed_for` victim from the queue."""
        self.queue.remove(victim)
        # counted under 'shed' ONLY (count=False): serve.cancelled
        # means cancel(rid), and summing the terminal counters + shed
        # must count every request exactly once
        self._retire(victim, 'cancelled',
                     reason=f'shed: displaced by higher-priority '
                            f'arrival (queue full at {self.max_queue})',
                     count=False)
        self.counts['shed'] += 1
        self._inc('serve.shed')

    def result(self, rid):
        """Terminal outcome of a request, handed over ONCE (removed
        from the engine on retrieval, so a long-running server does not
        accumulate one record per request ever served):

          - finished  -> the (prompt + max_new_tokens) ids (eos-padded
                         past an early stop, matching
                         DecodeEngine.generate);
          - failed    -> raises RequestFailed (`.error` = the cause);
          - expired   -> raises RequestExpired;
          - cancelled -> raises RequestCancelled (`.reason` says
                         whether cancel() or load shedding);
          - still pending (queued/running/preempted) -> None;
          - unknown rid (never submitted, or already retrieved)
                      -> raises KeyError(rid).
        """
        req = self._terminal.pop(rid, None)
        if req is None:
            if rid in self._live:
                return None
            raise KeyError(rid)
        self._collect_guard.discard(rid)
        if req.state == 'finished':
            return req.result
        cls = {'failed': RequestFailed, 'expired': RequestExpired,
               'cancelled': RequestCancelled}[req.state]
        raise cls(rid, req.reason, error=req.error)

    def status(self, rid):
        """Current state string for a known request (non-destructive —
        `result()` still hands the outcome over). KeyError when the rid
        is unknown or its result was already retrieved."""
        req = self._live.get(rid) or self._terminal.get(rid)
        if req is None:
            raise KeyError(rid)
        return req.state

    def cancel(self, rid):
        """Drop a request: frees its pages (running), removes it from
        the queue (queued/preempted — requeue-safe: a preempted
        request's stale heap entry is discarded lazily). Returns True
        when this call cancelled it, False when it was already
        terminal; KeyError for unknown rids. Takes effect at the host
        scheduler boundary — the engine is single-threaded."""
        req = self._live.get(rid)
        if req is None:
            if rid in self._terminal:
                return False
            raise KeyError(rid)
        if req.state in ('queued', 'preempted'):
            self.queue.remove(req)
        else:                     # running: release its slot and pages
            slot = self._slot_req.index(req)
            self._clear_slot(slot)
        self._retire(req, 'cancelled', reason='cancelled by caller')
        self._update_gauges()
        return True

    def drain(self, on=True):
        """Stop accepting new work while in-flight requests finish —
        the supervisor's rolling-restart first half (drain, wait for
        `in_flight() == 0` stepping the remainder out, `snapshot()`,
        hand off). While draining, `submit()` refuses with QueueFull
        (counted under 'rejected') and `/healthz` answers 503
        `{"status": "draining"}` so a router stops sending traffic
        immediately, whatever the SLO rules say. `drain(False)`
        reopens admission."""
        on = bool(on)
        if on == self.draining:
            return
        self.draining = on
        self._record('drain', on=on)
        self._set_gauge('serve.draining', 1.0 if on else 0.0)

    def close(self):
        """Release the engine's external resources — today that is the
        ops HTTP server's listening socket and thread (idempotent;
        engines without `ops_port` have nothing to release). The
        supervisor hand-off MUST call this on the old replica before
        binding a replacement on the same port: a daemon server thread
        dies with the process, not with the engine object, so two
        engine generations in one process would otherwise collide with
        EADDRINUSE."""
        if self.ops_server is not None:
            self.ops_server.close()
            self.ops_server = None

    def serve(self, prompts, max_new_tokens=None):
        """Submit + run + collect, preserving submission order.

        When a `max_queue` bound is configured, submission interleaves
        with scheduler steps (client backoff in miniature): a QueueFull
        reject drains one iteration and retries, so the convenience API
        never trips its own engine's admission control.
        """
        prompts = list(prompts)
        rids = []
        # guard this batch's terminal records against the max_terminal
        # eviction: serve() is the one caller that WILL collect every
        # record, so the bound that protects against abandonment must
        # not evict outputs the collection loop below is about to
        # return. result() releases each rid as it hands the outcome
        # over; after a raise below the remainder stays guarded (still
        # individually retrievable) until drained or until the next
        # serve() batch replaces the guard.
        self._collect_guard = set()
        for p in prompts:
            while True:
                try:
                    rid = self.submit(p, max_new_tokens)
                    break
                except QueueFull:
                    if self.draining:
                        raise       # stepping can never reopen a drain
                    self.step()
            rids.append(rid)
            self._collect_guard.add(rid)
        self.run()
        # surface the first failure BEFORE popping any finished record:
        # result() hands outcomes over destructively, so raising midway
        # through collection would throw away completed outputs — this
        # way they all stay individually retrievable via result()
        bad = next((r for r in rids if self.status(r) != 'finished'),
                   None)
        if bad is not None:
            self.result(bad)         # raises the typed terminal error
        return [self.result(r) for r in rids]

    def run(self, max_steps=None):
        """Step until queue and batch drain (or max_steps)."""
        steps = 0
        while len(self.queue) or self.in_flight():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return steps

    # -- crash-safe warm restart (snapshot / restore) ----------------------

    def _snapshot_config(self):
        """The config a snapshot must agree on to resume bit-equal:
        same model (structure hash — weights are the artifact's
        problem) and same sampling contract. Pool geometry is NOT here:
        a snapshot may restore into a bigger or smaller pool, each
        request re-validated for fit."""
        from .engine import model_struct, model_tag

        return {'model': model_tag(self.model),
                'model_struct': model_struct(self.model),
                'temperature': self.temperature, 'top_k': self.top_k,
                'top_p': self.top_p, 'eos_token_id': self.eos_token_id,
                'max_context_len': self.max_context_len}

    def _request_record(self, req, now):
        """One request as a JSON-serializable dict — the wire format
        `snapshot()` carries per request AND the `request` section of
        an `export_kv` migration blob (one schema, one versioning
        story: a blob survives exactly the process boundaries a
        snapshot does)."""
        return {
            'rid': req.rid, 'prompt': req.prompt.tolist(),
            'generated': [int(t) for t in req.generated],
            'max_new_tokens': req.max_new_tokens,
            'priority': req.priority, 'seq': req.seq,
            'state': req.state, 'reason': req.reason,
            'error': repr(req.error) if req.error is not None else None,
            'deadline_left_s': (req.deadline - now
                                if req.deadline is not None else None),
            'result': (req.result.tolist()
                       if req.result is not None else None),
            # per-request sampling params + the speculative carried
            # next-token (schema-1 compatible additions): a
            # restored sampled stream re-derives its stateless key
            # chain from (seed, generated index), and a restored
            # speculative stream resumes from exactly the verify's
            # pending choice — both bit-equal to uninterrupted
            'temperature': req.temperature, 'top_k': req.top_k,
            'top_p': req.top_p, 'sample_seed': req.sample_seed,
            'spec_next': req.spec_next,
        }

    def _rebuild_request(self, r, now):
        """Rebuild one `_request_record` dict into a live Request —
        the restore path's inverse, shared with `import_kv` (a
        migrated request keeps its identity: rid, sampling params,
        seed, generated prefix, remaining deadline, speculative carry
        all survive the hop)."""
        req = Request(r['rid'], r['prompt'], r['max_new_tokens'],
                      r['priority'],
                      temperature=r.get('temperature', self.temperature),
                      top_k=r.get('top_k', self.top_k),
                      top_p=r.get('top_p', self.top_p),
                      sample_seed=r.get('sample_seed'))
        if self._jr is not _journal.JOURNAL:
            req.journal = self._jr
        sn = r.get('spec_next')
        req.spec_next = int(sn) if sn is not None else None
        req.generated = [int(t) for t in r['generated']]
        req.seq = r['seq']
        req.state = r['state']
        req.reason = r['reason']
        req.error = r['error']          # repr string post-restore
        if r['result'] is not None:
            req.result = np.asarray(r['result'], np.int32)
        if r['deadline_left_s'] is not None:
            req.deadline = now + max(float(r['deadline_left_s']), 0.0)
        return req

    def snapshot(self):
        """JSON-serializable host state for crash recovery: every
        non-terminal request (queued / running / preempted — prompt,
        generated prefix, priority, remaining deadline, arrival seq)
        plus unretrieved terminal records, the rid/seq counters, and
        the sampling RNG key. ALL of it is host-authoritative — the
        device pools hold only KV rows that re-prefill reconstructs —
        so a supervisor can checkpoint at any scheduler boundary for
        the cost of a dict copy, rebuild a fresh engine from a PR-7
        AOT artifact, `restore()`, and finish every stream bit-equal
        to an uninterrupted greedy run (gate_resilience proves it)."""
        now = time.perf_counter()
        rec = functools.partial(self._request_record, now=now)
        live = ([rec(r) for r in self.queue]
                + [rec(r) for r in self._slot_req if r is not None])
        terminal = [rec(r) for r in self._terminal.values()]
        # flight-recorder trails ride the snapshot (JSON-able event
        # dicts), so a restored replica's `trail(rid)` is still one
        # ordered record from arrival to terminal state — restore()
        # re-injects them with the journal seq bumped past ours
        trails = {}
        if _journal.journal_enabled():
            for r in live + terminal:
                t = self._jr.trail(r['rid'])
                if t:
                    trails[str(r['rid'])] = t
        self._record('snapshot', requests=len(live),
                     terminal=len(terminal))
        return {
            'schema': SNAPSHOT_SCHEMA,
            'config': self._snapshot_config(),
            'requests': live,
            'terminal': terminal,
            'trails': trails,
            # SLO health history rides along (schema-1 compatible,
            # like 'trails'): a restored standby reports the primary's
            # breach state instead of silently re-arming every rule
            'watchdog': (self._watchdog.snapshot_state()
                         if self._watchdog is not None else None),
            'next_rid': self._rid,
            'preemptions': self.preemption_count,
            'counts': dict(self.counts),
            'prefix_counts': dict(self.prefix_counts),
            'spec_counts': dict(self.spec_counts),
            'migration_counts': dict(self.migration_counts),
            'tokens_out': self._tokens_out,
            'serve_time': self._serve_time,
            # the drain flag rides too (schema-1 compatible): a
            # standby resurrected from a draining primary's snapshot
            # must keep refusing submissions, or the router's drain
            # decision silently un-happens on failover
            'draining': self.draining,
        }

    def restore(self, snap):
        """Load a `snapshot()` into a FRESH engine (nothing submitted,
        nothing in flight). In-flight requests come back as
        'preempted' — they lost their slot to the crash and resume by
        re-prefilling prompt + generated prefix, the same machinery
        that makes ordinary preemption bit-equal. Deadlines re-arm from
        their remaining budget; rid/seq counters continue past the
        snapshot so new submissions never collide. Raises ValueError on
        a config mismatch (naming the differing fields) or a request
        that cannot fit THIS pool, RuntimeError when the engine is not
        fresh. Returns a report dict."""
        if (self.in_flight() or len(self.queue) or self._live
                or self._terminal or self._rid != self._rid_start):
            raise RuntimeError(
                'restore() needs a fresh engine: this one has requests '
                'queued, in flight, or unretrieved, or has already '
                'served traffic (its lifetime counters would be '
                'silently overwritten)')
        if snap.get('schema') != SNAPSHOT_SCHEMA:
            raise ValueError(
                f"unsupported snapshot schema {snap.get('schema')!r} "
                f'(this engine reads schema {SNAPSHOT_SCHEMA})')
        # name every missing required key at once, before any state is
        # touched — "KeyError: 'terminal'" from the middle of the loop
        # below names a symptom, not the defect (a truncated or
        # hand-built snapshot)
        missing = sorted(k for k in ('requests', 'terminal')
                         if k not in snap)
        if missing:
            raise ValueError(
                f'snapshot missing required key(s) {missing}: not a '
                f'ServingEngine.snapshot() dict (or truncated in '
                f'transit)')
        cfg = self._snapshot_config()
        got = snap.get('config', {})
        diff = sorted(k for k in cfg if got.get(k) != cfg[k])
        if diff:
            raise ValueError(
                f'snapshot config mismatch on {diff}: snapshot '
                f'{ {k: got.get(k) for k in diff} } vs engine '
                f'{ {k: cfg[k] for k in diff} }')
        now = time.perf_counter()
        max_seq = -1
        rebuild = functools.partial(self._rebuild_request, now=now)
        # validate EVERY request's fit before touching engine state: a
        # mid-loop raise would leave the standby half-restored (its
        # fresh-engine check then refuses a retry, and stepping it
        # would silently serve a subset of the snapshot's streams)
        for r in snap['requests']:
            total = len(r['prompt']) + r['max_new_tokens']
            if (total > self.max_context_len
                    or _ceil_div(total, self.block_size)
                    > self.allocator.usable):
                raise ValueError(
                    f"snapshot request {r['rid']} needs {total} context "
                    f'tokens — it cannot fit this engine '
                    f'(max_context_len {self.max_context_len}, '
                    f'{self.allocator.usable} usable pages)')
        # re-register the snapshot's flight-recorder trails FIRST (the
        # journal bumps its seq past the injected events), so the
        # 'restored'/'enqueued' marks below extend each trail in order;
        # a same-process hot standby shares the journal and injects
        # nothing (the trails are already there)
        for rid_s, evs in (snap.get('trails') or {}).items():
            self._jr.inject_trail(int(rid_s), evs)
        self._record('restore', requests=len(snap['requests']),
                     terminal=len(snap['terminal']))
        for r in snap['requests']:
            req = rebuild(r)
            if req.state == 'running':
                # its slot died with the old replica; re-enters as
                # preempted so it keeps arrival order and re-prefills
                req.state = 'preempted'
            max_seq = max(max_seq, req.seq if req.seq is not None else -1)
            req.mark('restored', state=req.state,
                     generated=len(req.generated))
            self._live[req.rid] = req
            if req.deadline is not None:
                self._deadlines_live += 1
            self.queue.push(req)
        for r in snap['terminal']:
            req = rebuild(r)
            max_seq = max(max_seq, req.seq if req.seq is not None else -1)
            self._terminal[req.rid] = req
        while len(self._terminal) > self.max_terminal:
            self._terminal.pop(next(iter(self._terminal)))
        self.queue.reset_seq(max_seq + 1)
        self._rid = max(int(snap.get('next_rid', 0)), self._rid)
        # monitoring continuity across the failover: the replica's
        # lifetime counters continue from the snapshot
        self.preemption_count = int(snap.get('preemptions', 0))
        for k, v in snap.get('counts', {}).items():
            if k in self.counts:
                self.counts[k] = int(v)
        for k, v in snap.get('prefix_counts', {}).items():
            if k in self.prefix_counts:
                self.prefix_counts[k] = int(v)
        for k, v in snap.get('spec_counts', {}).items():
            if k in self.spec_counts:
                self.spec_counts[k] = int(v)
        for k, v in snap.get('migration_counts', {}).items():
            if k in self.migration_counts:
                self.migration_counts[k] = int(v)
        self._tokens_out = int(snap.get('tokens_out', self._tokens_out))
        # without the matching serve-time, tokens_per_s would divide the
        # lifetime token total by the standby's near-zero wall time — a
        # phantom throughput spike on every failover
        self._serve_time = float(snap.get('serve_time', self._serve_time))
        # a draining primary's standby keeps refusing submissions (the
        # router decided to drain the REPLICA, not the process); older
        # snapshots without the key restore un-drained
        if snap.get('draining', False):
            self.draining = True
            self._set_gauge('serve.draining', 1.0)
        # older snapshots carry an 'rng' key from the pre-PR-15 shared
        # sampling stream; per-request stateless keys made it
        # meaningless, so it is accepted and ignored
        # continuous health history across the failover: rules matched
        # by name, so a standby with a tweaked ruleset still adopts
        # the states both sides define (a snapshot without watchdog
        # state — or a standby without a watchdog — is a no-op)
        if snap.get('watchdog') and self._watchdog is not None:
            self._watchdog.load_state(snap['watchdog'])
        self._update_gauges()
        return {'requests': len(snap['requests']),
                'terminal': len(snap['terminal']),
                'next_rid': self._rid}

    def adopt_request(self, record, trail=None):
        """Adopt ONE migrated request into this RUNNING engine — the
        fleet's scale-down path (docs/serving.md#fleet). `restore()`
        rebuilds a whole snapshot onto a fresh standby; a drain-
        migration instead scatters a victim replica's requests across
        survivors that are mid-serve, so this takes a single
        `_request_record` dict (+ its flight-recorder trail) and
        splices it in: terminal records land in `_terminal` (result()
        semantics unchanged — the rid answers on THIS replica now),
        live ones re-enter as preempted via the queue (their pages
        died with the victim; re-prefill reproduces the stream
        bit-equal, exactly the restore contract). Queue-bound exempt,
        like preemption requeues: migrated work was already admitted
        once. Raises ValueError on a rid collision (live, or terminal
        and unretrieved here) or a request this pool cannot fit —
        before any state is touched."""
        rid = int(record['rid'])
        if rid in self._live or rid in self._terminal:
            raise ValueError(
                f'adopt_request: rid {rid} already exists on this '
                f'engine — fleet rid_start strides must keep replica '
                f'id spaces disjoint')
        total = len(record['prompt']) + record['max_new_tokens']
        if (total > self.max_context_len
                or _ceil_div(total, self.block_size)
                > self.allocator.usable):
            raise ValueError(
                f'adopt_request: rid {rid} needs {total} context '
                f'tokens — it cannot fit this engine (max_context_len '
                f'{self.max_context_len}, {self.allocator.usable} '
                f'usable pages)')
        if trail:
            self._jr.inject_trail(rid, trail)
        now = time.perf_counter()
        req = self._rebuild_request(record, now=now)
        if req.state in ('finished', 'failed', 'expired', 'cancelled'):
            self._terminal[rid] = req
            while len(self._terminal) > self.max_terminal:
                self._terminal.pop(next(iter(self._terminal)))
            return rid
        if req.state == 'running':
            req.state = 'preempted'
        # fresh arrival seq on THIS engine: the victim's seq space can
        # collide with the survivor's, and a heap tie on (priority,
        # seq) would fall through to comparing Request objects
        req.seq = None
        req.mark('adopted', state=req.state,
                 generated=len(req.generated))
        self._live[rid] = req
        if req.deadline is not None:
            self._deadlines_live += 1
        self.queue.push(req)
        self._update_gauges()
        return rid

    # -- KV-cache migration (disaggregated prefill/decode serving) ---------

    def _blob_device_entries(self, pages, Cx, layers=None):
        """Device-resident per-layer scatter payloads for `_kv_import`,
        padded to the `Cx` bucket and uploaded replicated — ONE
        builder for the live import and the warmup dummy (layers=None
        -> zeros), so the warmed avals are the live ones by
        construction (the zero-mid-serve-compiles contract)."""
        from ..models.generation import RowQuantKVCache

        ents = []
        for li, pc in enumerate(pages):
            Hkv, D = int(pc.kp.shape[1]), int(pc.kp.shape[3])
            lay = layers[li] if layers is not None else None

            def up(field, shape, dtype):
                buf = np.zeros(shape, dtype)
                if lay is not None:
                    src = np.asarray(lay[field])
                    buf[0, :src.shape[0]] = src
                return self._put(buf)

            if hasattr(pc, 'ks'):
                ents.append(RowQuantKVCache(
                    up('k', (1, Cx, Hkv, D), np.int8),
                    up('v', (1, Cx, Hkv, D), np.int8),
                    up('ks', (1, Cx, Hkv), np.float32),
                    up('vs', (1, Cx, Hkv), np.float32)))
            else:
                dt = pc.kp.dtype
                ents.append((up('k', (1, Cx, Hkv, D), dt),
                             up('v', (1, Cx, Hkv, D), dt)))
        return ents

    def _check_blob_layers(self, name, layers, pages, n):
        """Structural validation of one blob KV group against THIS
        engine's pool before any allocator/block-table/pool mutation:
        layer count, field set, per-field dtype and row shape must be
        exactly what `_blob_device_entries` will scatter. A truncated
        or tampered blob fails here with the defect named — never
        mid-scatter with a broadcast error after pages were taken (the
        no-partial-scatter half of the atomic-placement contract)."""
        if not isinstance(layers, (list, tuple)) or len(layers) != len(pages):
            got = len(layers) if isinstance(layers, (list, tuple)) else \
                type(layers).__name__
            raise ValueError(
                f'corrupt KV blob: {name} carries {got} layer(s), this '
                f'engine scatters into {len(pages)}')
        for li, (lay, pc) in enumerate(zip(layers, pages)):
            Hkv, D = int(pc.kp.shape[1]), int(pc.kp.shape[3])
            if hasattr(pc, 'ks'):
                want = {'k': ((n, Hkv, D), np.dtype(np.int8)),
                        'v': ((n, Hkv, D), np.dtype(np.int8)),
                        'ks': ((n, Hkv), np.dtype(np.float32)),
                        'vs': ((n, Hkv), np.dtype(np.float32))}
            else:
                dt = np.dtype(pc.kp.dtype)
                want = {'k': ((n, Hkv, D), dt), 'v': ((n, Hkv, D), dt)}
            if not isinstance(lay, dict) or set(lay) != set(want):
                got = sorted(lay) if isinstance(lay, dict) else \
                    type(lay).__name__
                raise ValueError(
                    f'corrupt KV blob: {name}[{li}] fields {got} != '
                    f'expected {sorted(want)} for this pool')
            for field, (shape, dt) in want.items():
                a = np.asarray(lay[field])
                if tuple(a.shape) != shape or a.dtype != dt:
                    raise ValueError(
                        f'corrupt KV blob: {name}[{li}].{field} is '
                        f'{a.dtype}{tuple(a.shape)}, this pool scatters '
                        f'{dt}{shape}')

    @staticmethod
    def _blob_layer_bytes(blob):
        """Total payload bytes of a blob's KV arrays (target + draft) —
        the unit the bytes_exported/bytes_imported counters move in."""
        n = 0
        for group in ('layers', 'draft_layers'):
            for lay in blob.get(group) or []:
                n += sum(np.asarray(v).nbytes for v in lay.values())
        return n

    def export_kv(self, rid):
        """Gather running request `rid`'s paged KV (and draft KV when
        speculative) into one contiguous, process-portable migration
        blob — the prefill half of disaggregated serving
        (docs/serving.md#disaggregated-serving).

        The blob is a JSON-shaped dict plus numpy arrays: schema (1,
        shared with `snapshot()`), engine config, the full
        `_request_record` (identity, sampling params, seed, generated
        prefix, remaining deadline, speculative carry), per-layer
        contiguous K/V rows for positions [0, context_len - 1), and
        the request's flight-recorder trail. Int8 pools ship int8
        bytes + per-row f32 scales — BIT-identical pages at ~half the
        bf16 bytes. Position context_len - 1 is deliberately NOT
        shipped: the importer recomputes it through the existing
        continuation-chunk machinery, which also reproduces the next
        token's logits — so the migrated greedy stream is bit-equal
        to the source engine's own. Read-only: the request keeps
        serving here until its owner retires it (PrefillEngine's
        handoff sweep, or `cancel()`)."""
        t0 = time.perf_counter()
        req = self._live.get(rid)
        if req is None or req.state != 'running':
            state = req.state if req is not None else 'unknown/terminal'
            raise KeyError(
                f'export_kv needs a RUNNING request: rid {rid} is '
                f'{state!r} (queued/preempted requests have no pages '
                f'to export — snapshot() covers those)')
        slot = next(s for s, q in enumerate(self._slot_req) if q is req)
        if self._pfill[slot] is not None:
            raise RuntimeError(
                f'request {rid} is mid chunked prefill '
                f'({self._pfill[slot]}/{req.context_len} context tokens '
                f'in pages) — step until its prefill completes before '
                f'exporting')
        kvlen = req.context_len - 1
        if kvlen < 1:
            raise RuntimeError(
                f'request {rid} has no committed KV to export '
                f'(context_len {req.context_len})')
        Cx = bucket_length(kvlen, self.buckets)
        dkvlen = None
        with self._use_mesh():
            hit = self._note('serve_export', Cx)
            t_dispatch = time.perf_counter()
            btabs = self._put(self._btab[slot:slot + 1])
            st = self._put(np.asarray([kvlen], np.int32))
            out = _kv_export(self._pages, btabs, st, ctx_bucket=Cx)
            dout = None
            if self.draft is not None:
                # the draft pool's coverage can trail the target's
                # (window tokens the draft never saw) — ship what it
                # has; the importer's catch-up machinery fills the rest
                dkvlen = min(int(self._dctx[slot]), kvlen)
                dst = self._put(np.asarray([dkvlen], np.int32))
                dout = _kv_export(self._dpages, btabs, dst, ctx_bucket=Cx)
            host = jax.device_get(out)
            dhost = jax.device_get(dout) if dout is not None else None
        t_commit = time.perf_counter()
        if not hit:
            _obs_trace.compile_event(
                'compile:serve_export', key=('serve_export', Cx),
                dur_s=t_commit - t_dispatch,
                geometry=str(self._geometry()))
            self._record('compile', dispatch='serve_export',
                         key=str(('serve_export', Cx)),
                         dur_ms=round((t_commit - t_dispatch) * 1e3, 3))

        def crop(tmp, n):
            layers = []
            for t in tmp:
                if hasattr(t, 'ks'):
                    layers.append({'k': np.asarray(t.kq[0, :n]),
                                   'v': np.asarray(t.vq[0, :n]),
                                   'ks': np.asarray(t.ks[0, :n]),
                                   'vs': np.asarray(t.vs[0, :n])})
                else:
                    k, v = t
                    layers.append({'k': np.asarray(k[0, :n]),
                                   'v': np.asarray(v[0, :n])})
            return layers

        layers = crop(host, kvlen)
        draft_layers = crop(dhost, dkvlen) if dhost is not None else None
        nbytes = sum(v.nbytes for lay in layers for v in lay.values())
        if draft_layers is not None:
            nbytes += sum(v.nbytes for lay in draft_layers
                          for v in lay.values())
        # mark BEFORE snapshotting the trail, so the export event
        # itself rides the blob to the destination engine
        req.mark('kv_export', kv_len=kvlen, bytes=nbytes)
        blob = {
            'schema': SNAPSHOT_SCHEMA,
            'kind': KV_BLOB_KIND,
            'config': self._snapshot_config(),
            'kv_cache_dtype': (str(self.kv_cache_dtype)
                               if self.kv_cache_dtype else None),
            'block_size': self.block_size,
            'kv_len': kvlen,
            'request': self._request_record(req, time.perf_counter()),
            'layers': layers,
            'draft_kv_len': dkvlen,
            'draft_layers': draft_layers,
            'trail': (self._jr.trail(rid)
                      if _journal.journal_enabled() else []),
        }
        self.migration_counts['exported'] += 1
        self.migration_counts['bytes_exported'] += nbytes
        if _obs.enabled():
            self._metrics()['migration_ms'].observe(
                (time.perf_counter() - t0) * 1e3)
            self._inc('serve.kv_exported')
        return blob

    def import_kv(self, rid, blob):
        """Scatter an `export_kv` blob into THIS engine's pool and
        resume request `rid` — the decode half of disaggregated
        serving. The request re-enters as a one-token continuation
        chunk: the import places KV rows [0, kv_len) through the
        existing block-table machinery, then the next step's chunk
        dispatch recomputes position kv_len (= context_len - 1), which
        commits both that KV row and the first decode logits BIT-equal
        to the source engine's own step — no new dispatch kind, and
        the AOT-warmed chunk/import shapes cover it (zero mid-serve
        compiles on a warm-attached decode pool).

        Prefix-cache engines share full prompt pages below kv_len with
        the allocator's hash index (refcounts balanced); the page
        containing the recompute position stays private, so the import
        path never needs a CoW copy. Placement is ATOMIC: any failure
        — no free slot (QueueFull: retryable), a dry pool
        (OutOfBlocks), schema/config/dtype mismatch (ValueError) —
        rolls back every page and refcount taken and leaves the engine
        exactly as before the call. Returns the slot index."""
        t0 = time.perf_counter()
        rid = int(rid)
        if (blob.get('schema') != SNAPSHOT_SCHEMA
                or blob.get('kind') != KV_BLOB_KIND):
            raise ValueError(
                f"unsupported KV blob (schema {blob.get('schema')!r}, "
                f"kind {blob.get('kind')!r}): this engine reads "
                f"{KV_BLOB_KIND} schema {SNAPSHOT_SCHEMA}")
        # name every missing required key at once — a blob without its
        # request record or KV payload fails here with the defect
        # named, not as a KeyError from the placement machinery
        missing = sorted(k for k in ('request', 'kv_len', 'layers')
                         if k not in blob)
        if missing:
            raise ValueError(
                f'KV blob missing required key(s) {missing}: not an '
                f'export_kv blob (or stripped in transit)')
        cfg = self._snapshot_config()
        got_cfg = blob.get('config', {})
        diff = sorted(k for k in cfg if got_cfg.get(k) != cfg[k])
        if diff:
            raise ValueError(
                f'KV blob config mismatch on {diff}: blob '
                f'{ {k: got_cfg.get(k) for k in diff} } vs engine '
                f'{ {k: cfg[k] for k in diff} }')
        want = (str(self.kv_cache_dtype) if self.kv_cache_dtype else None)
        if blob.get('kv_cache_dtype') != want:
            raise ValueError(
                f"KV blob pool dtype {blob.get('kv_cache_dtype')!r} != "
                f'engine pool dtype {want!r}: migrating across '
                f'quantization worlds would break bit-equality — match '
                f'kv_cache_dtype across the pair')
        r = blob['request']
        if int(r['rid']) != rid:
            raise ValueError(f"blob carries rid {r['rid']}, not {rid}")
        if rid in self._live or rid in self._terminal:
            raise ValueError(
                f'rid {rid} is already registered on this engine — a '
                f'migrated request keeps its identity, so the '
                f'destination must not have seen it')
        if self.draft is not None and blob.get('draft_layers') is None:
            raise ValueError(
                'this engine is speculative but the blob carries no '
                'draft KV: export from a speculative source (or run '
                'the pair without a draft)')
        kvlen = int(blob['kv_len'])
        now = time.perf_counter()
        req = self._rebuild_request(r, now)
        if req.context_len != kvlen + 1:
            raise ValueError(
                f'corrupt KV blob: kv_len {kvlen} does not match the '
                f'carried request (context_len {req.context_len}; the '
                f'export contract is kv_len == context_len - 1)')
        total = len(req.prompt) + req.max_new_tokens
        if (total > self.max_context_len
                or _ceil_div(total, self.block_size)
                > self.allocator.usable):
            raise ValueError(
                f'imported request {rid} needs {total} context tokens — '
                f'it cannot fit this engine (max_context_len '
                f'{self.max_context_len}, {self.allocator.usable} '
                f'usable pages)')
        # structural check of every KV array BEFORE any allocator,
        # block-table, or pool mutation: a truncated/tampered blob
        # must leave the engine exactly as it found it
        self._check_blob_layers('layers', blob.get('layers'),
                                self._pages, kvlen)
        if self.draft is not None:
            self._check_blob_layers('draft_layers',
                                    blob.get('draft_layers'),
                                    self._dpages,
                                    int(blob.get('draft_kv_len') or 0))
        slot = next((s for s, q in enumerate(self._slot_req)
                     if q is None), None)
        if slot is None:
            raise QueueFull(
                f'no free slot for imported request {rid} '
                f'({self.max_slots} in flight) — retry after a step')
        a = self.allocator
        bs = self.block_size
        total_pages = _ceil_div(req.context_len, bs)
        shared: list = []
        if self.prefix_cache:
            hit_pages = a.match_prefix(prompt_page_hashes(req.prompt, bs))
            # share only pages FULLY below the recompute position: the
            # page holding position kvlen gets WRITTEN by the
            # continuation chunk, so it stays private — the import
            # path never needs a CoW copy (and has none to roll back)
            shared = hit_pages[:min(len(hit_pages), kvlen // bs)]
        pages: list = []
        try:
            a.phase = 'import'
            if shared:
                a.share(shared)
                pages.extend(shared)
            pages.extend(a.alloc(total_pages - len(shared)))
        except Exception:
            # atomic failure: return the shares (refcounts balanced),
            # free anything allocated, leave the pool untouched
            if pages:
                a.free(pages)
            self.migration_counts['import_failed'] += 1
            self._record('kv_import_failed', rid=rid, kv_len=kvlen)
            raise
        finally:
            a.phase = None
        Cx = bucket_length(kvlen, self.buckets)
        dkvlen = None
        if self.draft is not None:
            dkvlen = min(int(blob.get('draft_kv_len') or 0), kvlen)
        try:
            with self._use_mesh():
                reg_hit = self._note('serve_import', Cx)
                t_dispatch = time.perf_counter()
                pages_np = np.asarray(pages, np.int32)
                i = np.arange(Cx)
                blk = np.minimum(i // bs, len(pages) - 1)
                # rows the pool must NOT take from the blob — past the
                # export length, or covered by shared prefix pages —
                # scatter onto the reserved scratch page instead
                live_rows = (i < kvlen) & (i >= len(shared) * bs)
                sflat = self._put((i % bs).astype(np.int32))
                pflat = self._put(
                    np.where(live_rows, pages_np[blk], 0)
                    .astype(np.int32))
                ents = self._blob_device_entries(self._pages, Cx,
                                                 blob['layers'])
                self._pages = _kv_import(self._pages, ents, pflat,
                                         sflat, ctx_bucket=Cx)
                if self.draft is not None:
                    drows = (i < dkvlen) & (i >= len(shared) * bs)
                    dpflat = self._put(
                        np.where(drows, pages_np[blk], 0)
                        .astype(np.int32))
                    dents = self._blob_device_entries(
                        self._dpages, Cx, blob['draft_layers'])
                    self._dpages = _kv_import(self._dpages, dents,
                                              dpflat, sflat,
                                              ctx_bucket=Cx)
        except Exception:
            a.free(pages)
            self.migration_counts['import_failed'] += 1
            self._record('kv_import_failed', rid=rid, kv_len=kvlen)
            raise
        t_commit = time.perf_counter()
        if not reg_hit:
            _obs_trace.compile_event(
                'compile:serve_import', key=('serve_import', Cx),
                dur_s=t_commit - t_dispatch,
                geometry=str(self._geometry()))
            self._record('compile', dispatch='serve_import',
                         key=str(('serve_import', Cx)),
                         dur_ms=round((t_commit - t_dispatch) * 1e3, 3))
        # ONE trail follows the request across engines: re-register
        # the source's events FIRST (the journal bumps its seq past
        # them; a same-process pair shares the journal and injects
        # nothing), so the marks below extend the trail in order
        if blob.get('trail'):
            self._jr.inject_trail(rid, blob['trail'])
        self._live[rid] = req
        if req.deadline is not None:
            self._deadlines_live += 1
        self._place(slot, req, pages)
        # the import covers [0, kvlen); the continuation-chunk
        # machinery recomputes position kvlen from the carried tokens
        # on the next step (take=1 — its chunk bucket is warmed)
        self._pfill[slot] = kvlen
        self._cow_pending[slot] = None
        self._dctx[slot] = dkvlen if dkvlen is not None else kvlen
        if self.prefix_cache:
            # the imported rows ARE completed prompt KV: index the
            # full prompt pages now (shared ones stay with their first
            # writer), so later imports/admissions of the same prefix
            # hit — and count this import against the same hit/miss
            # telemetry the admission path feeds
            req.page_hashes = prompt_page_hashes(req.prompt, bs)
            self._register_prefix_pages(slot, req, 0, kvlen)
            if shared:
                self.prefix_counts['hits'] += 1
                self.prefix_counts['hit_tokens'] += len(shared) * bs
            else:
                self.prefix_counts['misses'] += 1
        self._rid = max(self._rid, rid + 1)
        nbytes = self._blob_layer_bytes(blob)
        req.mark('kv_import', kv_len=kvlen, bytes=nbytes, slot=slot,
                 shared_pages=len(shared))
        self.migration_counts['imported'] += 1
        self.migration_counts['bytes_imported'] += nbytes
        if _obs.enabled():
            self._metrics()['migration_ms'].observe(
                (time.perf_counter() - t0) * 1e3)
            self._inc('serve.kv_imported')
        self._update_gauges()
        return slot

    # -- the scheduler iteration -------------------------------------------

    def step(self):
        """One iteration: admit queued requests into free slots, top up
        pages for the coming window (preempting if the pool is dry),
        then run ONE fused jitted dispatch — admission prefill into the
        fresh pages composed with a decode window over ALL slots
        (_serve_step; _serve_window when nothing was admitted) — and
        finally commit tokens / retire finished rows from the single
        per-window host read. Returns the requests that finished this
        step.

        Telemetry rides the step's EXISTING host points: lifecycle
        timestamps and the ttft/itl/queue-wait histograms are all
        recorded at the per-window commit (right after the one
        device_get this loop already does), so instrumentation adds no
        sync and no retrace — bench.py's gate_observability_overhead
        and gate_serve_retrace_zero both hold it to that."""
        t0 = time.perf_counter()
        _step_span = _obs_trace.span('serve.step', cat='scheduler').begin()
        try:
            # the engine's mesh (None included) is pinned for the whole
            # iteration: any trace this step pays — first-time buckets,
            # chunk pairs — sees exactly the engine's sharding world
            with self._use_mesh():
                finished = self._step_impl(t0)
        except Exception as e:
            # the PR-8 worker-death path (a propagating window-dispatch
            # or top-up fault): drop the forensic bundle — metrics,
            # host trace, journal tail, restorable snapshot — BEFORE
            # re-raising, so the supervisor that restarts this replica
            # has the incident on disk
            self._auto_postmortem(e)
            raise
        finally:
            # ended in finally: a propagating window fault (worker
            # death) must not leak an open span into the host trace
            _step_span.end()
        # windowed timeseries + SLO watchdog ride the step boundary —
        # an existing host point that fires on EVERY outcome, including
        # a step whose whole admission group failed (nothing
        # dispatched, nothing committed — exactly the windows an
        # error-rate rule must see). OUTSIDE the try above: an
        # exception from a user-supplied on_breach callback must
        # surface as its own error, not masquerade as a worker death
        # and dump a false crash bundle. Off the interval boundary the
        # probe is two compares; on it, one pass over the registry
        # plus the rule evaluations — pure host arithmetic, zero new
        # syncs, zero retraces (gate_watchdog holds the tok/s ratio
        # within 3%)
        w = self._ts.maybe_commit(time.perf_counter())
        if w is not None and self._watchdog is not None:
            self._watchdog.evaluate(w, self._ts)
        return finished

    def _auto_postmortem(self, error):
        """Best-effort crash-bundle dump (enabled by `postmortem_dir`
        or PADDLE_TPU_POSTMORTEM_DIR; one numbered subdirectory per
        crash). NEVER raises — forensics must not mask the crash being
        recorded."""
        if not self.postmortem_dir:
            return
        try:
            from ..observability import postmortem as _postmortem

            self._postmortem_seq += 1
            out = os.path.join(
                self.postmortem_dir,
                f'postmortem-{os.getpid()}-{self._postmortem_seq}')
            self._record('postmortem', error=repr(error))
            _postmortem.dump_bundle(out, engine=self, error=error,
                                    reason='worker death in step()')
            self.last_postmortem = out
            self._inc('serve.postmortems')
        except Exception:  # noqa: BLE001 - never mask the real crash
            pass

    def _step_impl(self, t0):
        groups = self._admit()
        if not self.in_flight():
            self._serve_time += time.perf_counter() - t0
            self._update_gauges()   # admission may have expired/failed
            return []
        # assemble this step's CHUNK group: every slot mid chunked /
        # continuation prefill advances one chunk. Completions are
        # marked now — a slot whose last chunk commits this step
        # decodes its first window inside this very dispatch (the
        # monolithic _serve_step semantics), so the page top-up below
        # must already cover its window.
        chunk_rows = []
        for slot, req in enumerate(self._slot_req):
            p = self._pfill[slot]
            if req is None or p is None:
                continue
            take = req.context_len - p
            if self.prefill_chunk is not None:
                take = min(take, self.prefill_chunk)
            chunk_rows.append((slot, req, p, take))
        for slot, req, p, take in chunk_rows:
            self._pfill[slot] = (None if p + take >= req.context_len
                                 else p + take)
        if chunk_rows:
            self._dev = None
        try:
            self._ensure_window_pages()
        except Exception:
            # only an injected fault escapes the top-up (OutOfBlocks is
            # absorbed above): the 'preempt' seam, or a non-OutOfBlocks
            # alloc/free fault in the window phase. It models the
            # worker dying mid-eviction and PROPAGATES — but the groups
            # admitted THIS step have pages armed with no prefill run
            # yet, so they demote first (same hazard the window-seam
            # handler below covers), keeping the engine steppable in
            # place with sound KV on every surviving slot. Chunk rows
            # claimed progress whose dispatch now never runs — they
            # demote too and re-prefill from scratch on resume.
            for _Sb, g in groups:
                for slot, r in g:
                    if self._slot_req[slot] is r:
                        self._demote(slot, r)
            for slot, r, _p, _t in chunk_rows:
                if self._slot_req[slot] is r:
                    self._demote(slot, r)
            raise
        # the top-up above may have preempted (or failed) a
        # just-admitted request: drop it from the prefill groups (its
        # slot is parked on the scratch page; a preempted one
        # re-prefills when re-admitted)
        kept = []
        for Sb, g in groups:
            g = [(s, r) for s, r in g if self._slot_req[s] is r]
            if g:
                kept.append((Sb, g))
        groups = kept
        chunk_rows = [(s, r, p, t) for s, r, p, t in chunk_rows
                      if self._slot_req[s] is r]
        # the chunk group's fault seam (per-request isolation, same
        # contract as a prefill group: a scripted chunk fault fails the
        # affected rows, pages freed, the rest of the batch decodes on)
        if chunk_rows and not self._chunk_seam_ok(chunk_rows):
            chunk_rows = []
        W = self.decode_window
        # admissions beyond the fused dispatch prefill standalone (a
        # step that admits across buckets, or any monolithic admission
        # landing on a step where a chunk group holds the fused slot).
        # The 'dispatch' fault seam fires BEFORE each prefill dispatch
        # (per-request failure isolation: a fault scripted for a
        # request's prefill — the poisoned-request model — fails THAT
        # admission group, pages freed, and the rest of the batch keeps
        # decoding; the real dispatch is never interrupted mid-flight,
        # so donated buffers stay sound).
        standalone = groups if chunk_rows else groups[1:]
        for Sb, group in standalone:
            if not self._prefill_seam_ok(Sb, group):
                continue
            for _s, r in group:
                r.mark('prefill_dispatch', bucket=Sb, fused=False)
            self._prefill_group(Sb, group)
            if self.prefix_cache:
                for slot, r in group:
                    self._register_prefix_pages(slot, r, 0, r.context_len)
        fused = groups[0] if groups and not chunk_rows else None
        if fused is not None and not self._prefill_seam_ok(*fused):
            fused = None
        if not self.in_flight():
            # every live slot failed at its prefill seam: nothing to
            # decode this step, and step() must not abort
            self._serve_time += time.perf_counter() - t0
            self._update_gauges()
            return []
        dev = self._device_state()
        budget = self._put(self._budget)        # shrinks every window
        common = dict(window=W, eos_token_id=self.eos_token_id)
        spec = self.draft is not None and not chunk_rows
        sample_args = (dev['temp'], dev['topk'], dev['topp'],
                       dev['seed'], dev['plen'])
        # a fault scripted at kind='window' models the whole worker
        # dying mid-serve and PROPAGATES out of step() by design, so a
        # supervisor snapshots and restores — the crash path
        # tests/test_resilience.py and gate_resilience exercise. Before
        # it raises, the fused group admitted THIS step is demoted back
        # to the queue: its pages are armed but its prefill rides
        # inside the dispatch that now never runs, so leaving it
        # 'running' would let a caller who keeps stepping in place
        # decode uninitialized pages (the standalone prefills above
        # already completed — every other row's KV is sound either way)
        try:
            if _faults.ACTIVE is not None:       # skip ctx build when off
                _faults.fire('dispatch', kind='window',
                             in_flight=self.in_flight())
        except Exception:
            if fused is not None:
                for slot, r in fused[1]:
                    self._demote(slot, r)
            for slot, r, _p, _t in chunk_rows:
                if self._slot_req[slot] is r:
                    self._demote(slot, r)
            raise
        if spec:
            # the draft-dispatch fault seam (testing/faults.py): a
            # draft-model fault is ISOLATING, not a worker death — it
            # fails exactly the requests whose window needed the draft
            # (every live decoding slot this step, the fused admission
            # group included), pages freed, and the engine stays
            # steppable: queued requests admit next step and decode
            # bit-equal, nothing was dispatched with a half-written
            # draft cache
            try:
                if _faults.ACTIVE is not None:
                    _faults.fire(
                        'draft_dispatch', k=self.spec_window,
                        rids=[r.rid for s, r in enumerate(self._slot_req)
                              if r is not None
                              and self._pfill[s] is None])
            except Exception as e:  # noqa: BLE001 - scripted faults
                self._fail_group(
                    [(s, r) for s, r in enumerate(self._slot_req)
                     if r is not None and self._pfill[s] is None], e)
                self._serve_time += time.perf_counter() - t0
                self._update_gauges()
                return []
        spec_out = None
        t_dispatch = time.perf_counter()
        if spec:
            k = self.spec_window
            max_ctx = max(int(self._ctx[s])
                          for s, r in enumerate(self._slot_req)
                          if r is not None and self._pfill[s] is None)
            Sb_ctx = bucket_length(max_ctx + k + 1, self.buckets)
            ftok_d, forced_d = self._forced_state()
            # draft catch-up first (rows whose commits bypassed the
            # draft on a chunk step): the spec window's proposals must
            # run against complete draft KV. Sb_ctx covers every
            # row's end position by construction.
            catchup = self._draft_catchup_rows()
            fresh_draft = bool(catchup) and self._draft_advance(
                catchup, Sb_ctx)
            scommon = dict(k=k, ctx_bucket=Sb_ctx,
                           eos_token_id=self.eos_token_id)
            if fused is not None:
                Sb, group = fused
                for _s, r in group:
                    r.mark('prefill_dispatch', bucket=Sb, fused=True)
                ids, real_len, btabs, slots = self._prefill_args(Sb,
                                                                 group)
                hit = self._note('serve_spec_step', k, Sb, Sb_ctx)
                dispatch_key = ('serve_spec_step', k, Sb, Sb_ctx)
                (cand, nc, nxt, self._last_logits, self._pages,
                 self._dpages, ctx_out) = _serve_spec_step(
                    self.model, self.draft, self._pages, self._dpages,
                    self._last_logits, ids, real_len, btabs, slots,
                    ftok_d, forced_d, dev['btab'], dev['ctx'],
                    dev['live'], budget, *sample_args, **scommon)
                if self.prefix_cache:
                    for slot, r in group:
                        self._register_prefix_pages(slot, r, 0,
                                                    r.context_len)
            else:
                hit = self._note('serve_spec_window', k, Sb_ctx)
                dispatch_key = ('serve_spec_window', k, Sb_ctx)
                (cand, nc, nxt, self._last_logits, self._pages,
                 self._dpages, ctx_out) = _serve_spec_window(
                    self.model, self.draft, self._pages, self._dpages,
                    self._last_logits, ftok_d, forced_d, dev['btab'],
                    dev['ctx'], dev['live'], budget, *sample_args,
                    **scommon)
            spec_out = (cand, nc, nxt)
            # a fresh draft catch-up shape paid its compile inside
            # this step's wall: count the window as a MISS so the
            # compile time is excluded from ITL/MFU like any other
            hit = hit and not fresh_draft
            self.spec_counts['windows'] += 1
        elif chunk_rows:
            (ids, clen, cst, btabs, slots, cow_src, cow_dst, Cb,
             Sb) = self._chunk_args(chunk_rows)
            for _s, r, _p, _t in chunk_rows:
                r.mark('prefill_dispatch', chunk=True, start=_p, take=_t)
            hit = self._note('serve_chunk_step', W, Cb, Sb)
            dispatch_key = ('serve_chunk_step', W, Cb, Sb)
            if self.draft is not None:
                # keep the DRAFT's pages current through the chunk
                # path: same chunk/CoW args, logits commit dropped —
                # issued before the CoW pins are released below, so
                # both dispatches read the pinned source pages
                if (Cb, Sb) not in self._draft_shapes:
                    self._draft_shapes.add((Cb, Sb))
                    hit = False          # this step pays its compile
                self._dlogits, self._dpages = _draft_chunk(
                    self.draft, self._dpages, self._dlogits, ids, clen,
                    cst, btabs, self._dummy_slots, cow_src, cow_dst,
                    ctx_bucket=Sb)
                for s, _r, p, t in chunk_rows:
                    self._dctx[s] = p + t
                # decoding rows' draft holes (the PREVIOUS chunk-step
                # window's commits) catch up eagerly, so no hole ever
                # exceeds one window
                catchup = self._draft_catchup_rows()
                if catchup and self._draft_advance(
                        catchup,
                        bucket_length(max(p + t for _s, _r, p, t
                                          in catchup), self.buckets)):
                    hit = False
                # decoding rows may carry a pending verify-chosen next
                # token (spec_next): the chunk window consumes it as
                # each row's first token
                ftok_d, forced_d = self._forced_state()
            else:
                # non-speculative engines can never have forced rows —
                # the constant zero uploads skip the per-step scan
                ftok_d, forced_d = self._zero_ftok, self._zero_forced
            toks, self._last_logits, self._pages, ctx_out = \
                _serve_chunk_step(
                    self.model, self._pages, self._last_logits, ids,
                    clen, cst, btabs, slots, cow_src, cow_dst,
                    dev['btab'], dev['ctx'], dev['live'], budget,
                    *sample_args, ftok_d, forced_d, ctx_bucket=Sb,
                    **common)
            self.prefix_counts['chunk_steps'] += 1
            self._inc('serve.chunk_steps')
            if self._cow_release:
                # the dispatch carrying the CoW copies is issued: the
                # pinned source pages may now be freed (any future
                # writer of those pages is ordered after the copy by
                # the device dataflow through self._pages)
                self.allocator.free(self._cow_release)
                self._cow_release = []
            if self.prefix_cache:
                for slot, r, p, t in chunk_rows:
                    self._register_prefix_pages(slot, r, p, p + t)
        elif fused is not None:
            Sb, group = fused
            for _s, r in group:
                r.mark('prefill_dispatch', bucket=Sb, fused=True)
            ids, real_len, btabs, slots = self._prefill_args(Sb, group)
            hit = self._note('serve_step', W, Sb)
            dispatch_key = ('serve_step', W, Sb)
            toks, self._last_logits, self._pages, ctx_out = _serve_step(
                self.model, self._pages, self._last_logits, ids, real_len,
                btabs, slots, dev['btab'], dev['ctx'], dev['live'],
                budget, *sample_args, **common)
            if self.prefix_cache:
                for slot, r in group:
                    self._register_prefix_pages(slot, r, 0, r.context_len)
        else:
            hit = self._note('serve_window', W)
            dispatch_key = ('serve_window', W)
            toks, self._last_logits, self._pages, ctx_out = _serve_window(
                self.model, self._pages, self._last_logits,
                dev['btab'], dev['ctx'], dev['live'], budget,
                *sample_args, **common)
        # the returned ctx equals the host's post-commit view whenever
        # no slot is retired below (retiring invalidates the mirror)
        dev['ctx'] = ctx_out
        # ONE batched host read per window — the scheduler needs the
        # emitted tokens (and, speculatively, the per-slot accept
        # counts + carried next-token) to detect eos/budget and refill
        # the batch; all other state is host-authoritative.
        # tracelint: disable=TL002 - single sync per window by design
        if spec_out is not None:
            cand_h, nc_h, nxt_h = jax.device_get(spec_out)
            cand_h, nc_h, nxt_h = (np.asarray(cand_h), np.asarray(nc_h),
                                   np.asarray(nxt_h))
            tokens = None
        else:
            tokens = np.asarray(jax.device_get(toks))
        t_commit = time.perf_counter()
        if not hit:
            # a NEW registry key means this dispatch paid trace +
            # compile: surface it as a compile span whose wall duration
            # is dispatch-to-commit (trace + compile + first window)
            _obs_trace.compile_event(
                f'compile:{dispatch_key[0]}', key=dispatch_key,
                dur_s=t_commit - t_dispatch,
                geometry=str(self._geometry()))
            self._record(
                'compile', dispatch=dispatch_key[0],
                key=str(dispatch_key),
                dur_ms=round((t_commit - t_dispatch) * 1e3, 3))
        # steady-state per-token latency: the window advances every live
        # slot one token per scan step, so each committed token costs
        # window_wall / W — recorded once per token at this commit point
        # (window granularity, no per-token host syncs). A cache-MISS
        # window's wall is trace+compile, not decoding: its tokens are
        # excluded from the ITL histogram (they'd report compile time as
        # inter-token latency) and counted aside; TTFT keeps including
        # it — a request that waited on a compile really waited.
        per_tok_ms = ((t_commit - t_dispatch) * 1e3 / W) if hit else None
        telemetry = _obs.enabled()
        mx = self._metrics() if telemetry else None
        step_tokens = 0
        finished = []
        for slot, req in enumerate(self._slot_req):
            if req is None or self._pfill[slot] is not None:
                # mid-prefill slots rode the window frozen: they
                # emitted pad tokens and commit nothing until their
                # last chunk lands
                continue
            if spec_out is not None:
                # ragged speculative commit: the device already
                # clamped the accept count by budget and truncated at
                # eos (ncommit); the carried next-token persists on
                # the request so preemption/restore resumes bit-equal
                take = int(nc_h[slot])
                committed = [int(t) for t in cand_h[slot, :take]]
                req.spec_next = int(nxt_h[slot])
                # the draft scan wrote every committed position's KV
                self._dctx[slot] += take
                self.spec_counts['proposed'] += self.spec_window
                self.spec_counts['accepted'] += max(0, take - 1)
                if telemetry:
                    self._inc('serve.spec_proposed', self.spec_window)
                    self._inc('serve.spec_accepted', max(0, take - 1))
            else:
                take = min(W, req.remaining)
                committed = []
                for t in range(take):
                    tok = int(tokens[slot, t])
                    committed.append(tok)
                    if (self.eos_token_id is not None
                            and tok == self.eos_token_id):
                        break
                if committed:
                    # the window consumed any pending speculative
                    # carried token as its first commit (the forced
                    # path) — a stale spec_next must not force a later
                    # spec window at the wrong position
                    req.spec_next = None
            req.generated.extend(committed)
            self._ctx[slot] += len(committed)
            # keep the device-side freeze live: next window's budget is
            # the CURRENT remaining, so a continuing row can never
            # commit past its max_new on device and ctx_out stays equal
            # to the host view
            self._budget[slot] = req.remaining
            self._tokens_out += len(committed)
            step_tokens += len(committed)
            if telemetry and committed:
                itl_n = len(committed)
                if req.when('first_token') is None:
                    req.mark('first_token', t_commit)
                    arrived = req.when('arrival')
                    if arrived is not None:
                        mx['ttft'].observe((t_commit - arrived) * 1e3)
                    itl_n -= 1        # the first-ever token is TTFT
                row_ms = per_tok_ms
                if spec_out is not None and hit:
                    # ragged window: this row's per-token latency is
                    # the window wall over ITS committed count
                    row_ms = ((t_commit - t_dispatch) * 1e3
                              / max(len(committed), 1))
                if row_ms is not None:
                    mx['itl'].observe(row_ms, n=itl_n)
                else:
                    self._inc('serve.itl_skipped_compile', itl_n)
                req.mark('window', t_commit, n=len(committed),
                         total=len(req.generated))
            done = (req.remaining == 0
                    or (self.eos_token_id is not None and committed
                        and committed[-1] == self.eos_token_id))
            if done:
                self._finish(slot, req)
                finished.append(req)
            elif req.deadline is not None and t_commit >= req.deadline:
                # deadline check rides the existing per-window commit
                # sync (t_commit is already in hand — no extra clock
                # read, no device sync): an unfinished request past its
                # deadline expires HERE, pages freed, slot recycled
                self._clear_slot(slot)
                self._retire(
                    req, 'expired',
                    reason=f'deadline exceeded after '
                           f'{len(req.generated)} committed token(s)')
        self._serve_time += time.perf_counter() - t0
        if telemetry:
            mx['steps'].inc()
            mx['tokens'].inc(step_tokens)
            mx['step_ms'].observe((time.perf_counter() - t0) * 1e3)
            # live MFU / roofline: static flops of THIS dispatch's
            # geometry (the AOT manifest's cost stamp) over the
            # host-measured dispatch-to-commit wall — pure host
            # arithmetic on numbers already in hand (zero new syncs,
            # zero retraces). Cache-MISS windows are excluded like ITL:
            # their wall is trace+compile, not model execution.
            cost = (self._dispatch_costs.get(dispatch_key)
                    if self._dispatch_costs and hit else None)
            if cost is not None:
                wall = t_commit - t_dispatch
                fl = cost.get('flops')
                if fl and wall > 0:
                    fps = fl / wall
                    self._set_gauge('serve.model_flops_per_s', fps)
                    mfu = (fps / self._peak_flops
                           if self._peak_flops else None)
                    if mfu is not None:
                        self._set_gauge('serve.mfu_est', mfu)
                    ba = cost.get('bytes_accessed')
                    if ba:
                        self._set_gauge('serve.roofline_intensity',
                                        fl / ba)
                    self._last_mfu = {
                        'tag': dispatch_key, 'flops': fl,
                        'bytes_accessed': ba,
                        'window_wall_ms': wall * 1e3,
                        'flops_per_s': fps, 'mfu_est': mfu,
                        'peak_flops': self._peak_flops,
                    }
            self._update_gauges()
        return finished

    # -- internals ---------------------------------------------------------

    def _free_slots(self):
        return [i for i, r in enumerate(self._slot_req) if r is None]

    def _draft_catchup_rows(self):
        """Decoding slots whose draft pages lag their committed context
        (tokens a chunk-step's plain decode window committed never
        passed through the draft): (slot, req, start, take) rows for a
        `_draft_chunk` catch-up dispatch. Holes are bounded by one
        window per step (catch-up runs every speculative AND chunk
        step), so the take always buckets at or below the decode
        window's bucket."""
        rows = []
        for s, r in enumerate(self._slot_req):
            if r is None or self._pfill[s] is not None:
                continue
            hole = int(self._ctx[s]) - int(self._dctx[s])
            if hole > 0:
                rows.append((s, r, int(self._dctx[s]), hole))
        return rows

    def _draft_advance(self, rows, Sb):
        """One `_draft_chunk` dispatch appending each row's tokens
        [start, start+take) into the DRAFT's pages (no CoW — catch-up
        rows are past-prefill decoding slots), then advance their
        draft-valid context. Returns True when this (chunk bucket, ctx
        bucket) shape is NEW to the engine — its dispatch paid trace +
        compile, so the caller must count the step as a cache MISS
        (the wall would otherwise pollute the ITL/MFU gauges as decode
        time). Warmup drives the reachable shapes (`_warm_geometry`),
        so a warm-attached engine never sees a fresh one."""
        K = self.max_slots
        Cb = bucket_length(max(t for *_x, t in rows), self.buckets)
        fresh = (Cb, Sb) not in self._draft_shapes
        self._draft_shapes.add((Cb, Sb))
        ids = np.zeros((K, Cb), np.int32)
        clen = np.zeros((K,), np.int32)
        start = np.zeros((K,), np.int32)
        btabs = np.zeros((K, self.max_blocks_per_seq), np.int32)
        for i, (slot, req, p, take) in enumerate(rows):
            toks = np.concatenate([req.prompt,
                                   np.asarray(req.generated, np.int32)])
            ids[i, :take] = toks[p:p + take]
            clen[i] = take
            start[i] = p
            btabs[i] = self._btab[slot]
        z = self._put(np.zeros((K,), np.int32))
        self._dlogits, self._dpages = _draft_chunk(
            self.draft, self._dpages, self._dlogits, self._put(ids),
            self._put(clen), self._put(start), self._put(btabs),
            self._dummy_slots, z, z, ctx_bucket=Sb)
        for slot, req, p, take in rows:
            self._dctx[slot] = p + take
        return fresh

    def _forced_state(self):
        """Per-slot (forced_tok, forced) device args: rows carrying a
        speculative window's pending next-token choice (req.spec_next)
        commit it as their next token, whatever dispatch shape runs
        them. All-False on non-speculative engines (spec_next is never
        set) — the shared chunk-step trace stays identical."""
        forced = np.zeros((self.max_slots,), bool)
        ftok = np.zeros((self.max_slots,), np.int32)
        for s, r in enumerate(self._slot_req):
            if r is not None and r.spec_next is not None:
                forced[s] = True
                ftok[s] = r.spec_next
        return self._put(ftok), self._put(forced)

    def _device_state(self):
        """Device copies of the per-slot scheduler state, cached until
        a slot mutation invalidates them (self._dev = None). Slots mid
        chunked prefill ride the decode window FROZEN on the scratch
        page: their real block tables stay host-side (the chunk
        dispatch gets them as explicit args), so the window's clamped
        frozen-row write can never touch a page a chunk is still
        filling."""
        if self._dev is None:
            btab, ctx = self._btab, self._ctx
            live = [r is not None and self._pfill[i] is None
                    for i, r in enumerate(self._slot_req)]
            if any(p is not None for p in self._pfill):
                btab = btab.copy()
                ctx = ctx.copy()
                for i, p in enumerate(self._pfill):
                    if p is not None:
                        btab[i] = 0
                        ctx[i] = 0
            self._dev = {
                'btab': self._put(btab),
                'ctx': self._put(ctx),
                'live': self._put(np.asarray(live)),
                # per-slot sampling params ride the same slot-mutation
                # cadence (set at place, zeroed at clear) — a steady
                # window re-uses these uploads untouched
                'temp': self._put(self._temp),
                'topk': self._put(self._topk),
                'topp': self._put(self._topp),
                'seed': self._put(self._seed),
                'plen': self._put(self._plen),
            }
        return self._dev

    def _admit(self):
        """Fill free slots from the queue head (priority order — a head
        that cannot get its prefill pages waits, no barging past it).
        Returns this step's admissions grouped by prefill bucket,
        LARGEST group first (that one rides fused inside _serve_step;
        the batch width is pinned at max_slots with dummy rows masked
        to the scratch page, so the admission count never changes a
        traced shape)."""
        if not len(self.queue):
            # steady-state fast path: nothing to admit, skip even the
            # admit span (most steps of a drained-queue run land here)
            return []
        free = self._free_slots()
        placed = []
        admitted = 0
        a = self.allocator
        with _obs_trace.span('serve.admit', cat='scheduler') as _sp:
            while free and len(self.queue):
                req = self.queue.peek()
                if (req.deadline is not None
                        and time.perf_counter() >= req.deadline):
                    # expired while queued: never admitted, no prefill
                    # wasted on a stream nobody is waiting for anymore
                    self.queue.pop()
                    self._retire(req, 'expired',
                                 reason='deadline exceeded while queued')
                    continue
                total_pages = _ceil_div(req.context_len, self.block_size)
                hit = []
                hit_skipped = False
                if self.prefix_cache:
                    if req.page_hashes is None:
                        req.page_hashes = prompt_page_hashes(
                            req.prompt, self.block_size)
                    hit = a.match_prefix(req.page_hashes)
                if hit:
                    # profitability guard: a hit is taken only when it
                    # SHRINKS the prefill to a smaller bucket. A short
                    # hit on a short prompt lands in the same bucket —
                    # it saves (almost) no compute but pays the
                    # continuation gather and an extra chunk-step
                    # bookkeeping pass, a measured net loss on plain
                    # traffic. Skipped hits leave the pages cached for
                    # a longer-prefix arrival.
                    suffix = req.context_len - min(
                        len(hit) * self.block_size, req.context_len - 1)
                    if (bucket_length(suffix, self.buckets)
                            >= bucket_length(req.context_len,
                                             self.buckets)):
                        self.prefix_counts['hits_skipped'] += 1
                        hit = []
                        hit_skipped = True
                # continuation start: everything before it is valid KV
                # in shared pages. At least the LAST context token must
                # be recomputed (its logits seed the decode), so a
                # full-coverage hit backs off one token — into a shared
                # page, which the writer must copy-on-write first.
                start = min(len(hit) * self.block_size,
                            req.context_len - 1)
                cow = len(hit) * self.block_size > start
                need = total_pages - len(hit) + (1 if cow else 0)
                # cached pages the hit will revive stop being
                # allocatable the moment they are shared — the fresh
                # pages must fit in what remains, or the head waits
                # (checking available() alone would churn the LRU
                # through a share/unwind/re-park cycle every step)
                revive = sum(1 for p in hit if a.refcount(p) == 0)
                if need > a.available() - revive:
                    break
                held_after = a.in_use() + need + revive
                if (held_after / a.usable > self.admit_watermark
                        and self.in_flight() > 0):
                    # pool-pressure watermark: admitting would push the
                    # pool past the watermark and something is already
                    # running — hold the head back so decode windows
                    # top up from headroom instead of forcing a
                    # preemption storm. With NOTHING in flight the head
                    # always admits (forward progress beats pressure).
                    # Shared pages a hit would revive off the cached
                    # LRU count as pressure too.
                    self.counts['admission_paused'] += 1
                    self._inc('serve.admission_paused')
                    if self._paused_head != req.rid:
                        # edge-triggered: one trail event per stall,
                        # not one per paused scheduler step
                        self._paused_head = req.rid
                        self._record('admission_paused', rid=req.rid,
                                     held_after=held_after)
                    break
                self.queue.pop()
                got = []             # references to return on unwind
                cow_pair = None      # (src, dst): src ref is the PIN
                try:
                    if _faults.ACTIVE is not None:
                        _faults.fire('admit', rid=req.rid, need=need)
                    a.phase = 'admit'
                    if hit:
                        a.share(hit)
                        got.extend(hit)
                    if cow:
                        # the slot's page table carries the private
                        # copy; the reference on the SOURCE page stays
                        # held (allocator.cow's copy-pin contract) so
                        # no same-step allocation can harvest and
                        # overwrite it before the deferred device copy
                        # in the chunk dispatch reads it — released in
                        # _step_impl once that dispatch is issued (or
                        # by _clear_slot if the slot dies first)
                        cp = a.cow(hit[-1])
                        got.append(cp)
                        cow_pair = (hit[-1], cp)
                    got.extend(a.alloc(total_pages - len(hit)))
                except OutOfBlocks:
                    # transient pool pressure (an injected dry spell,
                    # or stats racing a concurrent free): release any
                    # shares already taken, requeue at the head, and
                    # stop admitting this step
                    if got:
                        a.free(got)
                    self.queue.push(req)
                    break
                except Exception as e:  # noqa: BLE001 - scripted faults
                    # a fault at THIS request's admission (the
                    # poisoned-request model): fail it alone — shares
                    # returned, zero leaked references — and keep
                    # admitting the rest of the queue
                    if got:
                        a.free(got)
                    self._retire(req, 'failed',
                                 reason=f'fault at admission: {e!r}',
                                 error=e)
                    continue
                finally:
                    a.phase = None
                if cow_pair is not None:
                    # page list for the slot: prefix with the private
                    # copy at the boundary position (the pinned source
                    # is NOT part of the slot's table)
                    pages_for_slot = (hit[:-1] + [cow_pair[1]]
                                      + got[len(hit) + 1:])
                else:
                    pages_for_slot = got
                slot = free.pop(0)
                self._place(slot, req, pages_for_slot)
                admitted += 1
                if self.prefix_cache:
                    if hit:
                        self.prefix_counts['hits'] += 1
                        self.prefix_counts['hit_tokens'] += start
                        self._inc('serve.prefix_hits')
                        self._inc('serve.prefix_hit_tokens', start)
                    elif not hit_skipped:
                        # a matched-but-unprofitable hit counts in
                        # NEITHER hits nor misses (hits_skipped above):
                        # hit rate = hits/(hits+misses) must read cache
                        # effectiveness, not the guard's declines
                        self.prefix_counts['misses'] += 1
                        self._inc('serve.prefix_misses')
                chunked = (self.prefill_chunk is not None
                           and req.context_len - start > self.prefill_chunk)
                if start > 0 or chunked:
                    # continuation / chunked admission: this slot rides
                    # the fused chunk dispatch (starting this very
                    # step) instead of the monolithic bucket prefill —
                    # it occupies its slot but emits no tokens until
                    # its last chunk commits
                    self._pfill[slot] = start
                    self._cow_pending[slot] = cow_pair
                    # the draft holds only the shared-prefix pages so
                    # far (valid: previous owners wrote them); its
                    # chunk legs advance this alongside the target's
                    self._dctx[slot] = start
                    if chunked:
                        self.prefix_counts['chunked_admissions'] += 1
                        self._inc('serve.chunked_admissions')
                else:
                    placed.append((slot, req))
            _sp.args['admitted'] = admitted
        by_bucket: dict = {}
        for slot, req in placed:
            Sb = bucket_length(req.context_len, self.buckets)
            by_bucket.setdefault(Sb, []).append((slot, req))
        return sorted(by_bucket.items(), key=lambda kv: -len(kv[1]))

    def _place(self, slot, req, pages):
        """Arm a slot (host bookkeeping only; the batched prefill in
        `_admit` moves the actual KV rows)."""
        self._slot_req[slot] = req
        self._slot_pages[slot] = pages
        self._btab[slot] = 0
        self._btab[slot, :len(pages)] = pages
        self._ctx[slot] = req.context_len
        self._budget[slot] = req.remaining
        self._temp[slot] = req.temperature
        self._topk[slot] = req.top_k
        self._topp[slot] = req.top_p
        self._seed[slot] = np.uint32(req.sample_seed)
        self._plen[slot] = len(req.prompt)
        # monolithic admissions prefill BOTH models this same step; a
        # chunk-path admission overrides this to its continuation start
        # right after placement (_admit)
        self._dctx[slot] = req.context_len
        self._dev = None
        req.state = 'running'
        req.admit_seq = next(self._admit_seq)
        self._paused_head = None     # admission resumed: re-arm the
                                     # admission_paused edge trigger
        req.mark('admitted', slot=slot, pages=len(pages))
        if _obs.enabled():
            self._inc('serve.admissions')
            if req.enqueued_at is not None:
                self._metrics()['qwait'].observe(
                    (time.perf_counter() - req.enqueued_at) * 1e3)
            _obs_trace.instant('serve.admission', cat='scheduler',
                               rid=req.rid, slot=slot, pages=len(pages))

    def _prefill_args(self, Sb, group):
        """Device args for one fixed-width admission-prefill batch
        (all of `group` shares bucket Sb; at most max_slots members —
        one per free slot). Rows beyond the group are dummies: real_len
        0 (their K/V land on the scratch page) and slot index SLOTS
        (their logits row is dropped by the OOB scatter)."""
        K = self.max_slots
        ids = np.zeros((K, Sb), np.int32)
        real_len = np.zeros((K,), np.int32)
        btabs = np.zeros((K, self.max_blocks_per_seq), np.int32)
        slots = np.full((K,), self.max_slots, np.int32)      # dummy: drop
        for i, (slot, req) in enumerate(group):
            toks = np.concatenate([req.prompt,
                                   np.asarray(req.generated, np.int32)])
            ids[i, :len(toks)] = toks                        # RIGHT-pad
            real_len[i] = len(toks)
            btabs[i] = self._btab[slot]
            slots[i] = slot
        return (self._put(ids), self._put(real_len),
                self._put(btabs), self._put(slots))

    def _prefill_group(self, Sb, group):
        """Standalone prefill dispatch for an admission group that did
        not fit the fused step (multi-bucket admission steps, or any
        monolithic admission landing on a step whose fused dispatch is
        the chunk group's). A speculative engine prefills the DRAFT's
        pages too — the draft must hold every admitted row's prompt KV
        or its proposals would be conditioned on zeros and the accept
        rate would silently collapse."""
        ids, real_len, btabs, slots = self._prefill_args(Sb, group)
        self._note('serve_prefill', Sb)
        self._last_logits, self._pages = _paged_prefill(
            self.model, self._pages, self._last_logits, ids, real_len,
            btabs, slots)
        if self.draft is not None:
            self._dlogits, self._dpages = _paged_prefill(
                self.draft, self._dpages, self._dlogits, ids, real_len,
                btabs, self._dummy_slots)

    def _chunk_args(self, rows):
        """Device args for one fixed-width chunk-continuation batch
        (the K-row discipline of `_prefill_args`: row i of the batch
        is rows[i] = (slot, req, progress, take); everything past the
        group is a dummy that lands on the scratch page and drops its
        logits). Returns the arrays plus the static (chunk bucket,
        context bucket) pair that keys the dispatch — row counts,
        chunk lengths, and per-row progress all ride as device data,
        so a whole long-prompt flood shares one compilation per
        bucket pair."""
        K = self.max_slots
        Cb = bucket_length(max(t for _s, _r, _p, t in rows), self.buckets)
        Sb = bucket_length(max(p + t for _s, _r, p, t in rows),
                           self.buckets)
        ids = np.zeros((K, Cb), np.int32)
        clen = np.zeros((K,), np.int32)
        start = np.zeros((K,), np.int32)
        btabs = np.zeros((K, self.max_blocks_per_seq), np.int32)
        slots = np.full((K,), self.max_slots, np.int32)   # dummy: drop
        cow_src = np.zeros((K,), np.int32)
        cow_dst = np.zeros((K,), np.int32)
        for i, (slot, req, p, take) in enumerate(rows):
            toks = np.concatenate([req.prompt,
                                   np.asarray(req.generated, np.int32)])
            ids[i, :take] = toks[p:p + take]
            clen[i] = take
            start[i] = p
            btabs[i] = self._btab[slot]
            if self._pfill[slot] is None:     # last chunk: commit logits
                slots[i] = slot
            pair = self._cow_pending[slot]
            if pair is not None:              # CoW rides the first chunk
                cow_src[i], cow_dst[i] = pair
                self._cow_pending[slot] = None
                # the copy-pin reference on the source drops once the
                # dispatch consuming this copy is issued (the caller
                # frees these right after the _serve_chunk_step call —
                # from then on the device dataflow orders any reuse of
                # the page after the copy that read it)
                self._cow_release.append(pair[0])
        return (self._put(ids), self._put(clen), self._put(start),
                self._put(btabs), self._put(slots),
                self._put(cow_src), self._put(cow_dst), Cb, Sb)

    def _chunk_seam_ok(self, rows):
        """Fire the per-dispatch fault seam for the chunk group
        (kind='chunk'). A scripted fault fails every member —
        per-request failure isolation, pages freed, shares returned —
        and returns False so the caller skips the chunk dispatch while
        the rest of the batch keeps decoding."""
        try:
            if _faults.ACTIVE is not None:       # skip ctx build when off
                _faults.fire('dispatch', kind='chunk',
                             rids=[r.rid for _s, r, _p, _t in rows])
        except Exception as e:  # noqa: BLE001 - scripted faults only
            self._fail_group([(s, r) for s, r, _p, _t in rows], e)
            return False
        return True

    def _register_prefix_pages(self, slot, req, lo, hi):
        """Bind the chain hash of every FULL prompt page whose KV the
        dispatch covering context positions [lo, hi) just completed.
        Only prompt-token pages index (generated tokens are
        per-request data); a hash already bound — shared pages, or a
        concurrent duplicate that computed the same block — stays with
        its first writer."""
        if req.page_hashes is None:
            return
        a = self.allocator
        pages = self._slot_pages[slot]
        bs = self.block_size
        for j in range(lo // bs, min(hi // bs, len(req.page_hashes))):
            a.register_prefix(pages[j], req.page_hashes[j])

    def _ensure_window_pages(self):
        """Every live slot must own pages covering the positions the
        coming window can write (ctx .. ctx + min(window, remaining)).
        A dry pool preempts the lowest-priority / youngest victim until
        the top-up fits (the needy slot may evict itself). A slot whose
        top-up STILL cannot be satisfied once it is the last request
        standing — maximal preemption reached — is unservable: that
        request fails alone (pages freed, pool invariants intact) and
        step() keeps decoding whatever remains; `OutOfBlocks` never
        escapes the scheduler."""
        a = self.allocator
        # per-step maximum commit: a speculative window can land up to
        # k+1 tokens (draft writes beyond the committed region fall on
        # the scratch page, so coverage only needs the committable max)
        adv = self.decode_window
        if self.spec_window is not None:
            adv = max(adv, self.spec_window + 1)
        for slot in range(self.max_slots):
            req = self._slot_req[slot]
            if req is None or self._pfill[slot] is not None:
                # mid-prefill slots already own every page their
                # admission allocated and ride the window frozen — no
                # top-up until their last chunk commits
                continue
            target = _ceil_div(
                int(self._ctx[slot]) + min(adv, req.remaining),
                self.block_size)
            while (self._slot_req[slot] is req
                   and target > len(self._slot_pages[slot])):
                try:
                    a.phase = 'window'
                    new = a.alloc(target - len(self._slot_pages[slot]))
                except OutOfBlocks as e:
                    others = any(
                        r is not None and s != slot
                        for s, r in enumerate(self._slot_req))
                    if others and self._preempt_one():
                        continue
                    # maximal preemption: this request is the only one
                    # left and a (nearly) drained pool still cannot
                    # cover its window — submit()'s fit check makes
                    # that unreachable for honest pools, so this is an
                    # injected fault or a snapshot restored into a
                    # smaller geometry; either way the REQUEST dies,
                    # never the step
                    self._clear_slot(slot)
                    self._retire(
                        req, 'failed',
                        reason=f'unservable: window page top-up failed '
                               f'after maximal preemption ({e})',
                        error=e)
                    break
                finally:
                    a.phase = None
                pages = self._slot_pages[slot]
                self._btab[slot, len(pages):len(pages) + len(new)] = new
                pages.extend(new)
                self._dev = None

    def _preempt_one(self):
        """Evict the lowest-priority (then youngest) in-flight request:
        free its pages, park the slot on the scratch page, requeue the
        request WITH its generated prefix (it resumes by re-prefill —
        greedy decoding makes the resumed stream identical to an
        uninterrupted one). Returns False when there is nothing to
        evict (the caller decides what dies; this never raises)."""
        victims = [(req.priority, -req.admit_seq, slot)
                   for slot, req in enumerate(self._slot_req)
                   if req is not None]
        if not victims:
            return False
        _, _, slot = min(victims)
        req = self._slot_req[slot]
        if _faults.ACTIVE is not None:
            _faults.fire('preempt', rid=req.rid, slot=slot)
        with _obs_trace.span('serve.preempt', cat='scheduler',
                             rid=req.rid, slot=slot,
                             generated=len(req.generated)):
            self._demote(slot, req)
        return True

    def _demote(self, slot, req):
        """Evict `slot` back to the queue as 'preempted' with full
        preemption bookkeeping (count, metric, lifecycle mark) — shared
        by pool-pressure eviction and the crash paths that requeue a
        just-admitted group whose prefill never ran, so a supervisor
        watching preemption rate sees every forced requeue."""
        self._clear_slot(slot)
        req.state = 'preempted'
        self.preemption_count += 1
        req.mark('preempted', generated=len(req.generated))
        self._inc('serve.preemptions')
        self.queue.push(req)

    def _retire(self, req, state, reason=None, error=None, result=None,
                count=True):
        """Move a request to its terminal state: stamp the lifecycle
        trail, count it (host counters work with telemetry off —
        stats() is truth), and park the record in `_terminal` for ONE
        `result()` retrieval. Callers release slot/queue residency
        first; this only flips the books. `count=False` lets a caller
        that owns its own counter (shedding) skip the per-state one, so
        every request lands in exactly one counter."""
        req.state = state
        req.reason = reason
        req.error = error
        if result is not None:
            req.result = result
        req.mark(state, reason=reason, tokens=len(req.generated))
        if count:
            self.counts[state] += 1
            self._inc(f'serve.{state}')
        if self._live.pop(req.rid, None) is not None \
                and req.deadline is not None:
            self._deadlines_live -= 1
        self._terminal[req.rid] = req
        while len(self._terminal) > self.max_terminal:
            victim = next((r for r in self._terminal
                           if r not in self._collect_guard), None)
            if victim is None:
                # every record belongs to an active serve() collection
                # — allow the overshoot (bounded by that one batch)
                # rather than evict outputs about to be returned
                break
            self._terminal.pop(victim)

    def _prefill_seam_ok(self, Sb, group):
        """Fire the per-prefill 'dispatch' fault seam for one admission
        group. A scripted fault fails the whole group (per-request
        failure isolation — the real dispatch is never interrupted
        mid-flight, so donated buffers stay sound) and returns False so
        the caller skips that prefill."""
        try:
            if _faults.ACTIVE is not None:       # skip ctx build when off
                _faults.fire('dispatch', kind='prefill', bucket=Sb,
                             rids=[r.rid for _s, r in group])
        except Exception as e:  # noqa: BLE001 - scripted faults only
            self._fail_group(group, e)
            return False
        return True

    def _fail_group(self, group, error):
        """Per-request failure isolation for one admission group whose
        prefill hit a fault: free each member's pages and fail it; the
        rest of the batch keeps decoding."""
        for slot, req in group:
            if self._slot_req[slot] is req:
                self._clear_slot(slot)
                self._retire(
                    req, 'failed',
                    reason=f'fault injected during prefill: {error!r}',
                    error=error)

    def _finish(self, slot, req):
        pad = self.eos_token_id if self.eos_token_id is not None else 0
        gen = (req.generated
               + [pad] * (req.max_new_tokens - len(req.generated)))
        out = np.concatenate(
            [req.prompt, np.asarray(gen, req.prompt.dtype)])
        self._clear_slot(slot)
        self._retire(req, 'finished', result=out)

    def _clear_slot(self, slot):
        self.allocator.free(self._slot_pages[slot])
        if self._cow_pending[slot] is not None:
            # the slot died before its first chunk dispatched: release
            # the copy-pin reference on the CoW source page too
            self.allocator.free([self._cow_pending[slot][0]])
        self._slot_req[slot] = None
        self._slot_pages[slot] = []
        self._btab[slot] = 0
        self._ctx[slot] = 0
        self._budget[slot] = 0
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        self._topp[slot] = 1.0
        self._seed[slot] = 0
        self._plen[slot] = 0
        self._dctx[slot] = 0
        self._pfill[slot] = None
        self._cow_pending[slot] = None
        self._dev = None


__all__ = ['ServingEngine', 'BlockAllocator', 'RequestQueue', 'Request',
           'OutOfBlocks', 'QueueFull', 'RequestError', 'RequestFailed',
           'RequestExpired', 'RequestCancelled', 'InvalidSamplingParams',
           'prompt_page_hashes']
