"""ServingEngine — continuous batching over a paged KV-cache block pool.

ref (capability): the reference serving stack's block_multihead_attention
paged caches + its request-level serving loop; design lineage: Orca
iteration-level scheduling over vLLM PagedAttention pages. PR 1's
DecodeEngine made a SINGLE static batch fast (one fused dispatch per
window, donated caches, zero steady-state retraces) but a request that
finishes early holds its padded slot until the whole batch drains and
new requests wait for a full generate() call. This module schedules at
the ITERATION level instead:

  1. `BlockAllocator` owns a pool of fixed-size KV pages shared by all
     in-flight requests (free-list alloc/free, page ids recycled
     LIFO, page 0 reserved as the scratch page inactive rows write to).
     The device pool arrays are allocated ONCE per engine
     (`model.init_paged_cache`) and never resized — allocation is pure
     id bookkeeping, so admitting/retiring a request moves zero cache
     bytes.

  2. `ServingEngine.step()` is one scheduler iteration over a FIXED-SLOT
     in-flight batch (`max_slots` rows, shapes never change):
       - retire/admit: finished rows already freed their pages; queued
         requests prefill into freshly allocated pages through the
         bucketed `_paged_prefill` (one compilation per bucket, the
         PR-1 discipline);
       - decode: ALL slots advance `decode_window` tokens in ONE fused
         jitted dispatch (`_serve_window`: a lax.scan whose single-token
         steps route the model through `cached_attention`'s
         PagedKVCache branch — the pallas paged kernel on TPU, a gather
         reference elsewhere), with ONE host sync per window to read
         the emitted tokens.
     Because slot count, page-pool shape, and window length are static,
     requests joining and leaving the batch never change a traced
     shape: steady-state serving is ZERO retraces (`trace_counts()`,
     shared with inference.engine, proves it; bench.py gates on it).

  3. Preemption: when the pool runs out of pages mid-decode, the
     lowest-priority (then youngest) in-flight request is EVICTED — its
     pages are freed, its prompt + generated prefix goes back to the
     queue — and later resumes by re-prefilling prompt+prefix (greedy
     decoding makes the resumed stream exactly the uninterrupted one).

Sampling config is pinned at engine construction (it is part of the
compilation key), greedy (temperature=0) is the parity-tested path:
per-request outputs are exactly `DecodeEngine.generate`'s batch-1
outputs. See docs/serving.md for the scheduler loop and the block-table
layout.
"""
from __future__ import annotations

import functools
import heapq
import inspect
import itertools
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import metrics as _obs
from ..observability import tracing as _obs_trace
from .engine import (COMPILE_CACHE, DEFAULT_BUCKETS, _count_trace,
                     bucket_length, total_traces, trace_counts)


class OutOfBlocks(RuntimeError):
    """The block pool cannot satisfy an allocation. The ServingEngine
    catches this and preempts; direct BlockAllocator users see it
    raised deterministically (need/have in the message)."""


class BlockAllocator:
    """Free-list allocator over a fixed pool of KV-cache pages.

    Pure id bookkeeping: the device page pools live in the engine and
    are NEVER reallocated — alloc/free hand out integer page ids, so
    the pool stays pointer-stable across any alloc/free sequence. Page
    0 is reserved as the scratch page (inactive/frozen slots write
    there), so usable capacity is num_blocks - 1 and every handed-out
    id is >= 1. Freed ids are reused LIFO (most-recently-freed first —
    deterministic, and the hottest pages stay hot)."""

    def __init__(self, num_blocks, block_size):
        num_blocks = int(num_blocks)
        if num_blocks < 2:
            raise ValueError(
                f'num_blocks must be >= 2 (page 0 is the reserved '
                f'scratch page), got {num_blocks}')
        self.num_blocks = num_blocks
        self.block_size = int(block_size)
        # LIFO stack, low ids on top: the first alloc after init hands
        # out 1, 2, ... in order (deterministic, test-friendly)
        self._free = list(range(num_blocks - 1, 0, -1))
        self._held: set = set()
        self.alloc_count = 0
        self.free_count = 0
        self.high_water = 0
        # device bytes one page costs across ALL layers (k + v), set by
        # the owning engine from the real pool arrays (the allocator
        # itself only moves ids); stats() reports real-unit pool sizes
        # once it is known
        self.bytes_per_page = None

    @property
    def usable(self):
        return self.num_blocks - 1

    def available(self):
        return len(self._free)

    def in_use(self):
        return len(self._held)

    def utilization(self):
        """Held fraction of the usable pool (scratch page excluded)."""
        return len(self._held) / max(self.usable, 1)

    def alloc(self, n):
        """n page ids, or OutOfBlocks (the pool is untouched on
        failure — no partial allocation to unwind)."""
        n = int(n)
        if n < 0:
            raise ValueError(f'cannot allocate {n} pages')
        if n > len(self._free):
            raise OutOfBlocks(
                f'need {n} page(s), {len(self._free)} free '
                f'({len(self._held)}/{self.usable} in use)')
        pages = [self._free.pop() for _ in range(n)]
        self._held.update(pages)
        self.alloc_count += n
        self.high_water = max(self.high_water, len(self._held))
        return pages

    def free(self, pages):
        """Return pages to the free list. Double-frees and foreign ids
        raise — both are allocator-corruption bugs worth failing on."""
        pages = list(pages)
        for p in pages:
            if p not in self._held:
                raise ValueError(
                    f'page {p} is not currently allocated '
                    f'(double-free or foreign id)')
        for p in pages:
            self._held.discard(p)
            self._free.append(p)
        self.free_count += len(pages)

    def stats(self):
        s = {
            'num_blocks': self.num_blocks,
            'block_size': self.block_size,
            'in_use': self.in_use(),
            'free': self.available(),
            'utilization': round(self.utilization(), 4),
            'high_water': self.high_water,
            'allocs': self.alloc_count,
            'frees': self.free_count,
        }
        if self.bytes_per_page:
            # real units: page counts x per-page KV bytes across all
            # layers and both of k/v, at the pool dtype — what an HBM
            # budget is actually written in
            bpp = int(self.bytes_per_page)
            s['bytes_per_page'] = bpp
            s['bytes_total'] = self.num_blocks * bpp
            s['bytes_in_use'] = self.in_use() * bpp
            s['bytes_high_water'] = self.high_water * bpp
        return s


class Request:
    """One serving request. `generated` accumulates committed tokens
    across admissions (a preempted request keeps its prefix and resumes
    by re-prefill over prompt + prefix).

    `times` is the lifecycle trail: (event, perf_counter) pairs stamped
    at arrival / enqueued / admitted / prefill_dispatch / first_token /
    window / preempted / finished — always at points the host already
    owns (submission, scheduling, the one per-window commit sync), so
    collecting them costs no device round trip. The engine rolls them
    into the registry's ttft/itl/queue-wait histograms."""

    __slots__ = ('rid', 'prompt', 'max_new_tokens', 'priority', 'generated',
                 'seq', 'state', 'admit_seq', 'times', 'enqueued_at')

    def __init__(self, rid, prompt, max_new_tokens, priority):
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.priority = int(priority)
        self.generated: list = []
        self.seq = None          # arrival order, stamped by RequestQueue
        self.admit_seq = None    # last admission order (preemption ties)
        self.state = 'queued'
        self.times: list = []
        self.enqueued_at = None

    def mark(self, event, t=None):
        """Append one lifecycle timestamp (no-op while telemetry is
        off, so a disabled server keeps zero per-request overhead).
        Callers that already hold a fresh perf_counter (the window
        commit loop stamps every slot at one instant) pass it as `t`
        instead of re-reading the clock per request."""
        if _obs.enabled():
            self.times.append(
                (event, time.perf_counter() if t is None else t))

    def when(self, event):
        """First timestamp for `event`, or None."""
        for e, t in self.times:
            if e == event:
                return t
        return None

    @property
    def remaining(self):
        return self.max_new_tokens - len(self.generated)

    @property
    def context_len(self):
        return len(self.prompt) + len(self.generated)


class RequestQueue:
    """Admission queue: higher `priority` first, FIFO within a
    priority. A preempted request keeps its original arrival seq, so it
    resumes ahead of later arrivals of the same priority."""

    def __init__(self):
        self._heap: list = []
        self._seq = itertools.count()

    def push(self, req):
        if req.seq is None:
            req.seq = next(self._seq)
        if req.state != 'preempted':     # keep eviction observable
            req.state = 'queued'
        # queue-wait accounting starts here (covers first arrival AND
        # every preemption requeue — a resumed request waits again)
        req.enqueued_at = time.perf_counter()
        req.mark('enqueued', req.enqueued_at)
        heapq.heappush(self._heap, (-req.priority, req.seq, req))

    def peek(self):
        return self._heap[0][2] if self._heap else None

    def pop(self):
        return heapq.heappop(self._heap)[2]

    def __len__(self):
        return len(self._heap)


# ---------------------------------------------------------------------------
# Module-level compiled steps (the persistent jit cache, PR-1 style)
# ---------------------------------------------------------------------------

def _prefill_body(model, pages, last_logits, ids, real_len, btabs, slots):
    """Bucketed BATCHED admission prefill INTO pages (traced body,
    shared by the standalone `_paged_prefill` jit and the fused
    `_serve_step`): run the model once over up to max_slots
    RIGHT-padded prompts (K, Sb) with a throwaway contiguous cache (the
    standard causal path — pad rows come after the real tokens, so rows
    < real_len never see them), then scatter every K/V row into its
    request's pages: row s of request b lands in page btabs[b, s // BS]
    slot s % BS, pad and DUMMY rows (real_len == 0) land on the scratch
    page 0, and each request's next-token logits land in its slot's row
    of `last_logits` (dummy rows carry slot == SLOTS, dropped by the
    out-of-bounds scatter). The batch width is FIXED at max_slots and
    real lengths ride as device data, so one compilation per bucket
    serves every admission count and every prompt length in the bucket
    — admitting requests costs one dispatch per (step, bucket), not
    one per request."""
    K, Sb = ids.shape
    tmp = model.init_cache(K, Sb)
    logits, tmp = model(ids, caches=tmp, cache_index=0)
    rl = jnp.reshape(jnp.asarray(real_len, jnp.int32), (K,))
    last = jnp.take_along_axis(
        logits, jnp.maximum(rl - 1, 0)[:, None, None], axis=1)[:, 0]
    bs = pages[0].kp.shape[2]
    maxb = btabs.shape[1]
    s = jnp.arange(Sb)
    blk = jnp.minimum(s // bs, maxb - 1)
    page = jnp.where(s[None, :] < rl[:, None],
                     jnp.take_along_axis(btabs, blk[None, :], axis=1),
                     0)                                       # (K, Sb)
    pflat = page.reshape(-1)
    sflat = jnp.broadcast_to(s % bs, (K, Sb)).reshape(-1)
    out_pages = []
    for (k, v), pc in zip(tmp, pages):
        rows = (K * Sb,) + k.shape[2:]
        kp = pc.kp.at[pflat, :, sflat, :].set(
            k.reshape(rows).astype(pc.kp.dtype))
        vp = pc.vp.at[pflat, :, sflat, :].set(
            v.reshape(rows).astype(pc.vp.dtype))
        out_pages.append(type(pc)(kp, vp))
    last_logits = last_logits.at[slots].set(
        last.astype(last_logits.dtype), mode='drop')
    return last_logits, out_pages


def _window_body(model, pages, last_logits, btab, ctx, live, budget,
                 rng_key, *, window, temperature, top_k, top_p,
                 eos_token_id):
    """One decode window for the whole fixed-slot batch as ONE compiled
    lax.scan (traced body, shared by `_serve_window` and the fused
    `_serve_step`): per step, sample every slot's next token from the
    carried logits, step the model over the paged caches (per-row write
    positions = ctx, attention through the block tables), advance the
    committed length of live rows. Rows freeze when they hit eos, burn
    their budget, or were never live (empty slots): frozen rows still
    ride through the static-shape forward but write only to their
    frozen position / the scratch page and commit nothing — exactly how
    requests leave the batch without changing a traced shape. Returns
    (tokens (SLOTS, window), last_logits, pages, ctx); the host reads
    the tokens ONCE per window and does all bookkeeping there."""

    def sample(logits, key):
        from ..models.generation import filter_logits

        logits = filter_logits(
            logits.astype(jnp.float32) / temperature, top_k, top_p)
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

    pad_tok = eos_token_id if eos_token_id is not None else 0

    def step(carry, t):
        last_logits, pages, ctx, finished, key = carry
        if temperature == 0.0:
            tok = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
        else:
            key, sub = jax.random.split(key)
            tok = sample(last_logits, sub)
        frozen = finished | (t >= budget)
        tok = jnp.where(frozen, jnp.asarray(pad_tok, tok.dtype), tok)
        commit = ~frozen
        if eos_token_id is not None:
            finished = finished | (commit & (tok == eos_token_id))
        logits, pages = model(tok[:, None], caches=pages,
                              kv_write_pos=ctx, block_tables=btab)
        ctx = ctx + commit.astype(jnp.int32)
        return (logits[:, -1, :], pages, ctx, finished, key), tok

    state = (last_logits, pages, jnp.asarray(ctx, jnp.int32), ~live,
             rng_key)
    (last_logits, pages, ctx, _, _), toks = jax.lax.scan(
        step, state, jnp.arange(window, dtype=jnp.int32))
    return toks.T, last_logits, pages, ctx


@functools.partial(jax.jit, donate_argnames=('pages', 'last_logits'))
def _paged_prefill(model, pages, last_logits, ids, real_len, btabs, slots):
    """Standalone admission prefill (see _prefill_body) — used only for
    the rare step that admits across SEVERAL buckets at once; the first
    (largest) bucket group rides fused inside _serve_step."""
    _count_trace('serve_prefill')
    return _prefill_body(model, pages, last_logits, ids, real_len, btabs,
                         slots)


@functools.partial(
    jax.jit, donate_argnames=('pages', 'last_logits'),
    static_argnames=('window', 'temperature', 'top_k', 'top_p',
                     'eos_token_id'))
def _serve_window(model, pages, last_logits, btab, ctx, live, budget,
                  rng_key, *, window, temperature, top_k, top_p,
                  eos_token_id):
    """A pure decode window (no admissions this step): see
    _window_body."""
    _count_trace('serve_window')
    return _window_body(model, pages, last_logits, btab, ctx, live,
                        budget, rng_key, window=window,
                        temperature=temperature, top_k=top_k, top_p=top_p,
                        eos_token_id=eos_token_id)


@functools.partial(
    jax.jit, donate_argnames=('pages', 'last_logits'),
    static_argnames=('window', 'temperature', 'top_k', 'top_p',
                     'eos_token_id'))
def _serve_step(model, pages, last_logits, ids, real_len, btabs, slots,
                btab, ctx, live, budget, rng_key, *, window, temperature,
                top_k, top_p, eos_token_id):
    """THE scheduler iteration as one fused jitted dispatch: freshly
    admitted rows bucket-prefill into their newly allocated pages
    (_prefill_body), then every slot — new and old — decodes a window
    through the paged kernel (_window_body). One compilation per
    (bucket, window) pair covers every admission count; a step with no
    admissions uses _serve_window instead."""
    _count_trace('serve_step')
    last_logits, pages = _prefill_body(model, pages, last_logits, ids,
                                       real_len, btabs, slots)
    return _window_body(model, pages, last_logits, btab, ctx, live,
                        budget, rng_key, window=window,
                        temperature=temperature, top_k=top_k, top_p=top_p,
                        eos_token_id=eos_token_id)


def _ceil_div(a, b):
    return -(-a // b)


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

class ServingEngine:
    """Continuous-batching serving over one model.

        engine = ServingEngine(model, max_slots=8, num_blocks=...,
                               max_new_tokens=64, eos_token_id=2)
        rid = engine.submit(prompt_ids)          # 1-D int array
        engine.run()                             # drain queue + batch
        out = engine.result(rid)                 # (S + max_new,) ids

        outs = engine.serve(list_of_prompts)     # submit+run+collect

    Greedy outputs per request are exactly `DecodeEngine.generate`'s
    batch-1 outputs (eos-padded to max_new_tokens, prompt echoed back).
    The model must accept `block_tables` in its cached forward (the
    Llama family does) and must not use sliding-window attention.
    """

    def __init__(self, model, max_slots=8, block_size=16, num_blocks=None,
                 max_context_len=None, max_new_tokens=32, decode_window=8,
                 temperature=0.0, top_k=0, top_p=1.0, eos_token_id=None,
                 buckets=None):
        params = inspect.signature(model.forward).parameters
        if 'block_tables' not in params:
            raise NotImplementedError(
                f'{type(model).__name__} lacks block_tables in its '
                f'cached forward: paged serving needs the Llama-family '
                f'cached_attention; use DecodeEngine for this model')
        if getattr(getattr(model, 'config', None), 'sliding_window',
                   None) is not None:
            raise NotImplementedError(
                'sliding-window models are not paged-servable yet: the '
                'paged kernel has no window fast path — use DecodeEngine')
        self.model = model
        self.max_slots = int(max_slots)
        self.block_size = int(block_size)
        self.max_new_tokens = int(max_new_tokens)
        self.decode_window = int(decode_window)
        if self.decode_window < 1 or self.max_slots < 1:
            raise ValueError('decode_window and max_slots must be >= 1')
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos_token_id = (int(eos_token_id) if eos_token_id is not None
                             else None)
        self.buckets = tuple(sorted(buckets)) if buckets else DEFAULT_BUCKETS
        if max_context_len is None:
            mp = getattr(getattr(model, 'config', None),
                         'max_position_embeddings', None)
            max_context_len = int(mp) if mp else 2048
        self.max_context_len = int(max_context_len)
        self.max_blocks_per_seq = _ceil_div(self.max_context_len,
                                            self.block_size)
        if num_blocks is None:
            # full coverage: every slot can hold a max-length request
            # (+1 for the reserved scratch page); pass a smaller pool to
            # actually exercise preemption
            num_blocks = self.max_slots * self.max_blocks_per_seq + 1
        self.allocator = BlockAllocator(num_blocks, self.block_size)
        self.queue = RequestQueue()

        # device state, allocated ONCE (shapes never change)
        self._pages = model.init_paged_cache(num_blocks, self.block_size)
        # real-unit pool accounting: one page costs k+v bytes per layer
        # at the pool dtype (pages x page_bytes x layers x dtype) —
        # threaded into allocator.stats() and the pool.* gauges
        self.allocator.bytes_per_page = int(sum(
            2 * int(np.prod(pc.kp.shape[1:])) * pc.kp.dtype.itemsize
            for pc in self._pages))
        vocab = model.config.vocab_size
        self._last_logits = jnp.zeros((self.max_slots, vocab),
                                      model.cache_dtype())
        self._rng = jax.random.PRNGKey(0)

        # host-authoritative per-slot state (device copies ride in as
        # small int32/bool args each window)
        self._slot_req: list = [None] * self.max_slots
        self._slot_pages: list = [[] for _ in range(self.max_slots)]
        self._btab = np.zeros((self.max_slots, self.max_blocks_per_seq),
                              np.int32)
        self._ctx = np.zeros((self.max_slots,), np.int32)
        self._budget = np.zeros((self.max_slots,), np.int32)
        # device mirror of (btab, ctx, live): rebuilt only when a slot
        # changes (admission/retire/preempt/page top-up); between those
        # the window's returned ctx is carried device-resident, so a
        # steady-state window uploads ONE small array (the budgets)
        self._dev = None

        self._results: dict = {}
        self._rid = itertools.count()
        self._admit_seq = itertools.count()
        self.preemption_count = 0
        self._tokens_out = 0
        self._serve_time = 0.0
        # telemetry hot-path caches: metric handles (refreshed when the
        # registry generation changes, i.e. after a reset) and the last
        # occupancy tuple (gauges re-set only when it moves) — keeps
        # per-step recording to a handful of attribute writes so the
        # 3% overhead gate holds even on tiny/fast models
        self._mgen = -1
        self._mx = None
        self._last_occ = None
        self._update_gauges()

    # -- bookkeeping -------------------------------------------------------

    def _sampling_key(self):
        return (self.max_new_tokens, self.temperature, self.top_k,
                self.top_p, self.eos_token_id)

    def _geometry(self):
        return ('paged', self.max_slots, self.allocator.num_blocks,
                self.block_size, self.max_blocks_per_seq)

    def registry_key(self, *tag):
        """The EXACT CompileCache key `_note(*tag)` records (the shared
        recipe: pool shape + dtype + sampling config + `tag` +
        geometry). Tags are the dispatch kinds step() uses:
        ('serve_step', W, Sb), ('serve_window', W),
        ('serve_prefill', Sb). Exposed so aot.GeometrySet enumeration
        and the live engine provably agree key-for-key."""
        return COMPILE_CACHE.key(
            self.model, self._pages[0].kp.shape, self.model.cache_dtype(),
            self._sampling_key() + tag, geometry=self._geometry())

    def _note(self, *tag):
        """Record one engine-level registry key. Returns the registry
        verdict — True on hit, False when the key is NEW (this dispatch
        pays trace + compile; step() turns that into a compile span
        with the measured wall duration)."""
        return COMPILE_CACHE.note(self.registry_key(*tag))

    def _metrics(self):
        """Cached registry handles for the hot per-step records (the
        generation check makes a registry reset() safe: stale handles
        are re-resolved instead of written into orphaned objects)."""
        R = _obs.REGISTRY
        if self._mgen != R.generation:
            self._mx = {
                'ttft': R.histogram('serve.ttft_ms'),
                'itl': R.histogram('serve.itl_ms'),
                'qwait': R.histogram('serve.queue_wait_ms'),
                'step_ms': R.histogram('serve.step_ms'),
                'steps': R.counter('serve.steps'),
                'tokens': R.counter('serve.tokens'),
                'in_flight': R.gauge('serve.in_flight'),
                'queue_depth': R.gauge('serve.queue_depth'),
                'pages_in_use': R.gauge('pool.pages_in_use'),
                'util': R.gauge('pool.utilization'),
                'bytes_in_use': R.gauge('pool.bytes_in_use'),
                'bytes_total': R.gauge('pool.bytes_total'),
            }
            self._mgen = R.generation
            self._last_occ = None          # force a gauge refresh
        return self._mx

    def _update_gauges(self):
        """Occupancy/pool gauges, refreshed at the step boundary only
        when occupancy actually moved (host bookkeeping only; a steady
        full batch skips all six writes)."""
        if not _obs.enabled():
            return
        m = self._metrics()
        a = self.allocator
        occ = (self.in_flight(), len(self.queue), a.in_use())
        if occ == self._last_occ:
            return
        self._last_occ = occ
        m['in_flight'].set(occ[0])
        m['queue_depth'].set(occ[1])
        m['pages_in_use'].set(occ[2])
        m['util'].set(a.utilization())
        if a.bytes_per_page:
            m['bytes_in_use'].set(occ[2] * a.bytes_per_page)
            m['bytes_total'].set(a.num_blocks * a.bytes_per_page)

    def in_flight(self):
        return sum(r is not None for r in self._slot_req)

    def stats(self):
        """Serving observability: throughput, occupancy, pool
        utilization, scheduling counters, and the shared retrace
        counters (steady-state serving must hold total_traces flat —
        bench.py's gate_serve_retrace_zero asserts it)."""
        return {
            'trace_counts': trace_counts(),
            'total_traces': total_traces(),
            'tokens_generated': self._tokens_out,
            'tokens_per_s': (self._tokens_out / self._serve_time
                             if self._serve_time > 0 else 0.0),
            'in_flight': self.in_flight(),
            'queue_depth': len(self.queue),
            'preemptions': self.preemption_count,
            'blocks': self.allocator.stats(),
            'geometry': {'kind': 'paged', 'max_slots': self.max_slots,
                         'block_size': self.block_size,
                         'num_blocks': self.allocator.num_blocks,
                         'max_blocks_per_seq': self.max_blocks_per_seq,
                         'decode_window': self.decode_window},
        }

    # -- AOT artifact hooks (paddle_tpu.aot) -------------------------------

    def aot_config(self):
        """Compilation-relevant config as a dict of primitives (what
        two engines must share for one EngineArtifact to serve both;
        weights are structure, not values — see DecodeEngine)."""
        from .engine import model_struct, model_tag

        return {
            'engine': 'ServingEngine',
            'model': model_tag(self.model),
            'model_struct': model_struct(self.model),
            'cache_dtype': str(self.model.cache_dtype()),
            'max_slots': self.max_slots,
            'block_size': self.block_size,
            'num_blocks': self.allocator.num_blocks,
            'max_context_len': self.max_context_len,
            'max_new_tokens': self.max_new_tokens,
            'decode_window': self.decode_window,
            'temperature': self.temperature,
            'top_k': self.top_k,
            'top_p': self.top_p,
            'eos_token_id': self.eos_token_id,
            'buckets': list(self.buckets),
        }

    def _aot_jitted_fns(self):
        """The module-level jitted steps this engine's geometries
        dispatch — what `aot.build` cache-evicts (per FUNCTION, not
        process-wide) to force real persisting compiles."""
        return (_paged_prefill, _serve_window, _serve_step)

    def _warm_geometry(self, g, draft=None):
        """Drive ONE enumerated geometry through the SAME module-level
        jitted steps the scheduler dispatches, with an all-dummy slot
        batch: real_len 0 rows land on the scratch page, slot indices
        max_slots drop their logits on the OOB scatter, and live=False
        freezes every row — so warming an IDLE engine (enforced below)
        mutates no scheduler state beyond the (donated, re-assigned)
        device pools. The args come from the same builders step() uses
        (`_prefill_args`, `_device_state`), so the traced avals are the
        live ones by construction."""
        p = g.params
        W = self.decode_window
        if p.get('window', W) != W:
            raise ValueError(
                f'geometry {g.label()} was enumerated for decode_window '
                f"{p['window']}, engine has {W}")
        if self.in_flight():
            # the dummy batch is only inert when every slot is empty: a
            # LIVE row would really decode through the dummy window
            # (pages written, last_logits advanced) while the host
            # mirror commits nothing — silent token corruption for
            # every in-flight request
            raise RuntimeError(
                f'cannot warm a ServingEngine with {self.in_flight()} '
                f'request(s) in flight: drain the batch (run()) before '
                f'warmup/aot.build')
        dev = self._device_state()
        budget = jnp.asarray(self._budget)
        common = dict(window=W, temperature=self.temperature,
                      top_k=self.top_k, top_p=self.top_p,
                      eos_token_id=self.eos_token_id)
        # a fixed dummy key with the live aval: warming must NOT
        # consume the engine's sampling stream (self._rng), or a warmed
        # and an unwarmed replica seeded identically would emit
        # different sampled tokens
        sub = jax.random.PRNGKey(0)
        if g.kind == 'serve_step':
            ids, real_len, btabs, slots = self._prefill_args(p['bucket'], [])
            self._note('serve_step', W, p['bucket'])
            _, self._last_logits, self._pages, _ = _serve_step(
                self.model, self._pages, self._last_logits, ids, real_len,
                btabs, slots, dev['btab'], dev['ctx'], dev['live'], budget,
                sub, **common)
        elif g.kind == 'serve_window':
            self._note('serve_window', W)
            _, self._last_logits, self._pages, _ = _serve_window(
                self.model, self._pages, self._last_logits, dev['btab'],
                dev['ctx'], dev['live'], budget, sub, **common)
        elif g.kind == 'serve_prefill':
            ids, real_len, btabs, slots = self._prefill_args(p['bucket'], [])
            self._note('serve_prefill', p['bucket'])
            self._last_logits, self._pages = _paged_prefill(
                self.model, self._pages, self._last_logits, ids, real_len,
                btabs, slots)
        else:
            raise ValueError(f'unknown serving geometry kind {g.kind!r}')

    def warmup(self, artifact=None, geometries=None, draft=None):
        """Pre-populate the module-level jit caches (and the
        CompileCache registry) for every geometry this engine's config
        implies, BEFORE the first request — with an `aot.EngineArtifact`
        the compiles are persistent-cache disk reads, so a fresh
        replica's first request is ZERO compiles. Returns a report
        dict; see docs/aot_warmup.md."""
        from ..aot.artifact import warm_attach

        return warm_attach(self, artifact=artifact, geometries=geometries,
                           draft=draft)

    def _export_specs(self, g, draft=None):
        """(suffix, jitted_fn, args) for `aot.build(...,
        export_stablehlo=True)`. The model is closed over (the jit.save
        idiom — a Layer in the calling convention would refuse to
        serialize); the page pools stay ARGS, as ShapeDtypeStruct avals
        of the engine's live pools (they are state, not weights — the
        exported module must take them, and PagedKVCache is a
        registered serializable container)."""
        p = g.params
        W = self.decode_window
        K = self.max_slots

        def sds(x):
            return jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), x)

        pages = sds(self._pages)
        logits = sds(self._last_logits)
        btab = jax.ShapeDtypeStruct((K, self.max_blocks_per_seq),
                                    jnp.int32)
        ctx = jax.ShapeDtypeStruct((K,), jnp.int32)
        live = jax.ShapeDtypeStruct((K,), jnp.bool_)
        budget = jax.ShapeDtypeStruct((K,), jnp.int32)
        common = dict(window=W, temperature=self.temperature,
                      top_k=self.top_k, top_p=self.top_p,
                      eos_token_id=self.eos_token_id)
        if g.kind in ('serve_step', 'serve_prefill'):
            ids = jax.ShapeDtypeStruct((K, int(p['bucket'])), jnp.int32)
            rl = jax.ShapeDtypeStruct((K,), jnp.int32)
            btabs = jax.ShapeDtypeStruct((K, self.max_blocks_per_seq),
                                         jnp.int32)
            slots = jax.ShapeDtypeStruct((K,), jnp.int32)

        def wrap(base, **statics):
            # tracelint: disable=TL001 - one-shot export wrapper (model
            # and statics baked into the closure; never a hot path)
            return jax.jit(functools.partial(
                getattr(base, '__wrapped__', base), self.model, **statics))

        if g.kind == 'serve_step':
            yield ('', wrap(_serve_step, **common),
                   (pages, logits, ids, rl, btabs, slots, btab, ctx,
                    live, budget, self._rng))
        elif g.kind == 'serve_window':
            yield ('', wrap(_serve_window, **common),
                   (pages, logits, btab, ctx, live, budget, self._rng))
        elif g.kind == 'serve_prefill':
            yield ('', wrap(_paged_prefill),
                   (pages, logits, ids, rl, btabs, slots))
        else:
            raise NotImplementedError(
                f'no StableHLO export for geometry kind {g.kind!r}')

    # -- public API --------------------------------------------------------

    def submit(self, prompt, max_new_tokens=None, priority=0):
        """Queue one request; returns its id for `result()`. Validated
        against the pool so an undeliverable request fails HERE, not as
        a livelock mid-serve."""
        mnt = (self.max_new_tokens if max_new_tokens is None
               else int(max_new_tokens))
        if mnt < 1:
            raise ValueError('max_new_tokens must be >= 1')
        req = Request(next(self._rid), prompt, mnt, priority)
        if len(req.prompt) == 0:
            raise ValueError('empty prompt')
        total = len(req.prompt) + mnt
        if total > self.max_context_len:
            raise ValueError(
                f'prompt + max_new_tokens = {total} exceeds '
                f'max_context_len {self.max_context_len}')
        if _ceil_div(total, self.block_size) > self.allocator.usable:
            raise ValueError(
                f'request needs {_ceil_div(total, self.block_size)} '
                f'pages but the pool only has {self.allocator.usable} '
                f'usable — grow num_blocks')
        req.mark('arrival')
        _obs.inc('serve.requests')
        self.queue.push(req)
        return req.rid

    def result(self, rid):
        """(prompt + max_new_tokens) ids for a finished request (eos-
        padded past an early stop, matching DecodeEngine.generate);
        None while pending. The output is handed over ONCE — it is
        removed from the engine on retrieval, so a long-running server
        does not accumulate one array per request ever served."""
        return self._results.pop(rid, None)

    def serve(self, prompts, max_new_tokens=None):
        """Submit + run + collect, preserving submission order."""
        rids = [self.submit(p, max_new_tokens) for p in prompts]
        self.run()
        return [self._results.pop(r) for r in rids]

    def run(self, max_steps=None):
        """Step until queue and batch drain (or max_steps)."""
        steps = 0
        while len(self.queue) or self.in_flight():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return steps

    # -- the scheduler iteration -------------------------------------------

    def step(self):
        """One iteration: admit queued requests into free slots, top up
        pages for the coming window (preempting if the pool is dry),
        then run ONE fused jitted dispatch — admission prefill into the
        fresh pages composed with a decode window over ALL slots
        (_serve_step; _serve_window when nothing was admitted) — and
        finally commit tokens / retire finished rows from the single
        per-window host read. Returns the requests that finished this
        step.

        Telemetry rides the step's EXISTING host points: lifecycle
        timestamps and the ttft/itl/queue-wait histograms are all
        recorded at the per-window commit (right after the one
        device_get this loop already does), so instrumentation adds no
        sync and no retrace — bench.py's gate_observability_overhead
        and gate_serve_retrace_zero both hold it to that."""
        t0 = time.perf_counter()
        _step_span = _obs_trace.span('serve.step', cat='scheduler').begin()
        groups = self._admit()
        if not self.in_flight():
            self._serve_time += time.perf_counter() - t0
            _step_span.end()
            return []
        self._ensure_window_pages()
        # the top-up above may have preempted a just-admitted request:
        # drop it from the prefill groups (its slot is parked on the
        # scratch page; it re-prefills when re-admitted)
        kept = []
        for Sb, g in groups:
            g = [(s, r) for s, r in g if self._slot_req[s] is r]
            if g:
                kept.append((Sb, g))
        groups = kept
        W = self.decode_window
        if self.temperature != 0.0:
            self._rng, sub = jax.random.split(self._rng)
        else:
            sub = self._rng               # unused inside a greedy trace
        # admissions beyond the first bucket group (rare: a step that
        # admits across buckets) prefill standalone; the first group
        # rides inside the fused step
        for Sb, group in groups[1:]:
            for _s, r in group:
                r.mark('prefill_dispatch')
            self._prefill_group(Sb, group)
        dev = self._device_state()
        budget = jnp.asarray(self._budget)      # shrinks every window
        common = dict(window=W, temperature=self.temperature,
                      top_k=self.top_k, top_p=self.top_p,
                      eos_token_id=self.eos_token_id)
        t_dispatch = time.perf_counter()
        if groups:
            Sb, group = groups[0]
            for _s, r in group:
                r.mark('prefill_dispatch')
            ids, real_len, btabs, slots = self._prefill_args(Sb, group)
            hit = self._note('serve_step', W, Sb)
            dispatch_key = ('serve_step', W, Sb)
            toks, self._last_logits, self._pages, ctx_out = _serve_step(
                self.model, self._pages, self._last_logits, ids, real_len,
                btabs, slots, dev['btab'], dev['ctx'], dev['live'],
                budget, sub, **common)
        else:
            hit = self._note('serve_window', W)
            dispatch_key = ('serve_window', W)
            toks, self._last_logits, self._pages, ctx_out = _serve_window(
                self.model, self._pages, self._last_logits,
                dev['btab'], dev['ctx'], dev['live'], budget, sub,
                **common)
        # the returned ctx equals the host's post-commit view whenever
        # no slot is retired below (retiring invalidates the mirror)
        dev['ctx'] = ctx_out
        # ONE batched host read per window — the scheduler needs the
        # emitted tokens to detect eos/budget and refill the batch; all
        # other state is host-authoritative.
        # tracelint: disable=TL002 - single sync per window by design
        tokens = np.asarray(jax.device_get(toks))
        t_commit = time.perf_counter()
        if not hit:
            # a NEW registry key means this dispatch paid trace +
            # compile: surface it as a compile span whose wall duration
            # is dispatch-to-commit (trace + compile + first window)
            _obs_trace.compile_event(
                f'compile:{dispatch_key[0]}', key=dispatch_key,
                dur_s=t_commit - t_dispatch,
                geometry=str(self._geometry()))
        # steady-state per-token latency: the window advances every live
        # slot one token per scan step, so each committed token costs
        # window_wall / W — recorded once per token at this commit point
        # (window granularity, no per-token host syncs). A cache-MISS
        # window's wall is trace+compile, not decoding: its tokens are
        # excluded from the ITL histogram (they'd report compile time as
        # inter-token latency) and counted aside; TTFT keeps including
        # it — a request that waited on a compile really waited.
        per_tok_ms = ((t_commit - t_dispatch) * 1e3 / W) if hit else None
        telemetry = _obs.enabled()
        mx = self._metrics() if telemetry else None
        step_tokens = 0
        finished = []
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            take = min(W, req.remaining)
            committed = []
            for t in range(take):
                tok = int(tokens[slot, t])
                committed.append(tok)
                if self.eos_token_id is not None and tok == self.eos_token_id:
                    break
            req.generated.extend(committed)
            self._ctx[slot] += len(committed)
            # keep the device-side freeze live: next window's budget is
            # the CURRENT remaining, so a continuing row can never
            # commit past its max_new on device and ctx_out stays equal
            # to the host view
            self._budget[slot] = req.remaining
            self._tokens_out += len(committed)
            step_tokens += len(committed)
            if telemetry and committed:
                itl_n = len(committed)
                if req.when('first_token') is None:
                    req.mark('first_token', t_commit)
                    arrived = req.when('arrival')
                    if arrived is not None:
                        mx['ttft'].observe((t_commit - arrived) * 1e3)
                    itl_n -= 1        # the first-ever token is TTFT
                if per_tok_ms is not None:
                    mx['itl'].observe(per_tok_ms, n=itl_n)
                else:
                    _obs.inc('serve.itl_skipped_compile', itl_n)
                req.mark('window', t_commit)
            done = (req.remaining == 0
                    or (self.eos_token_id is not None and committed
                        and committed[-1] == self.eos_token_id))
            if done:
                self._finish(slot, req)
                finished.append(req)
        self._serve_time += time.perf_counter() - t0
        if telemetry:
            mx['steps'].inc()
            mx['tokens'].inc(step_tokens)
            mx['step_ms'].observe((time.perf_counter() - t0) * 1e3)
            self._update_gauges()
        _step_span.end()
        return finished

    # -- internals ---------------------------------------------------------

    def _free_slots(self):
        return [i for i, r in enumerate(self._slot_req) if r is None]

    def _device_state(self):
        """Device copies of the per-slot scheduler state, cached until
        a slot mutation invalidates them (self._dev = None)."""
        if self._dev is None:
            self._dev = {
                'btab': jnp.asarray(self._btab),
                'ctx': jnp.asarray(self._ctx),
                'live': jnp.asarray(
                    np.asarray([r is not None for r in self._slot_req])),
            }
        return self._dev

    def _admit(self):
        """Fill free slots from the queue head (priority order — a head
        that cannot get its prefill pages waits, no barging past it).
        Returns this step's admissions grouped by prefill bucket,
        LARGEST group first (that one rides fused inside _serve_step;
        the batch width is pinned at max_slots with dummy rows masked
        to the scratch page, so the admission count never changes a
        traced shape)."""
        if not len(self.queue):
            # steady-state fast path: nothing to admit, skip even the
            # admit span (most steps of a drained-queue run land here)
            return []
        free = self._free_slots()
        placed = []
        with _obs_trace.span('serve.admit', cat='scheduler') as _sp:
            while free and len(self.queue):
                req = self.queue.peek()
                need = _ceil_div(req.context_len, self.block_size)
                if need > self.allocator.available():
                    break
                self.queue.pop()
                slot = free.pop(0)
                pages = self.allocator.alloc(need)
                self._place(slot, req, pages)
                placed.append((slot, req))
            _sp.args['admitted'] = len(placed)
        by_bucket: dict = {}
        for slot, req in placed:
            Sb = bucket_length(req.context_len, self.buckets)
            by_bucket.setdefault(Sb, []).append((slot, req))
        return sorted(by_bucket.items(), key=lambda kv: -len(kv[1]))

    def _place(self, slot, req, pages):
        """Arm a slot (host bookkeeping only; the batched prefill in
        `_admit` moves the actual KV rows)."""
        self._slot_req[slot] = req
        self._slot_pages[slot] = pages
        self._btab[slot] = 0
        self._btab[slot, :len(pages)] = pages
        self._ctx[slot] = req.context_len
        self._budget[slot] = req.remaining
        self._dev = None
        req.state = 'running'
        req.admit_seq = next(self._admit_seq)
        req.mark('admitted')
        if _obs.enabled():
            _obs.inc('serve.admissions')
            if req.enqueued_at is not None:
                self._metrics()['qwait'].observe(
                    (time.perf_counter() - req.enqueued_at) * 1e3)
            _obs_trace.instant('serve.admission', cat='scheduler',
                               rid=req.rid, slot=slot, pages=len(pages))

    def _prefill_args(self, Sb, group):
        """Device args for one fixed-width admission-prefill batch
        (all of `group` shares bucket Sb; at most max_slots members —
        one per free slot). Rows beyond the group are dummies: real_len
        0 (their K/V land on the scratch page) and slot index SLOTS
        (their logits row is dropped by the OOB scatter)."""
        K = self.max_slots
        ids = np.zeros((K, Sb), np.int32)
        real_len = np.zeros((K,), np.int32)
        btabs = np.zeros((K, self.max_blocks_per_seq), np.int32)
        slots = np.full((K,), self.max_slots, np.int32)      # dummy: drop
        for i, (slot, req) in enumerate(group):
            toks = np.concatenate([req.prompt,
                                   np.asarray(req.generated, np.int32)])
            ids[i, :len(toks)] = toks                        # RIGHT-pad
            real_len[i] = len(toks)
            btabs[i] = self._btab[slot]
            slots[i] = slot
        return (jnp.asarray(ids), jnp.asarray(real_len),
                jnp.asarray(btabs), jnp.asarray(slots))

    def _prefill_group(self, Sb, group):
        """Standalone prefill dispatch for an admission group that did
        not fit the fused step (multi-bucket admission steps)."""
        ids, real_len, btabs, slots = self._prefill_args(Sb, group)
        self._note('serve_prefill', Sb)
        self._last_logits, self._pages = _paged_prefill(
            self.model, self._pages, self._last_logits, ids, real_len,
            btabs, slots)

    def _ensure_window_pages(self):
        """Every live slot must own pages covering the positions the
        coming window can write (ctx .. ctx + min(window, remaining)).
        A dry pool preempts the lowest-priority / youngest victim until
        the top-up fits (the needy slot may evict itself)."""
        for slot in range(self.max_slots):
            req = self._slot_req[slot]
            if req is None:
                continue
            target = _ceil_div(
                int(self._ctx[slot]) + min(self.decode_window,
                                           req.remaining),
                self.block_size)
            while (self._slot_req[slot] is req
                   and target > len(self._slot_pages[slot])):
                try:
                    new = self.allocator.alloc(
                        target - len(self._slot_pages[slot]))
                except OutOfBlocks:
                    self._preempt_one()
                    continue
                pages = self._slot_pages[slot]
                self._btab[slot, len(pages):len(pages) + len(new)] = new
                pages.extend(new)
                self._dev = None

    def _preempt_one(self):
        """Evict the lowest-priority (then youngest) in-flight request:
        free its pages, park the slot on the scratch page, requeue the
        request WITH its generated prefix (it resumes by re-prefill —
        greedy decoding makes the resumed stream identical to an
        uninterrupted one)."""
        victims = [(req.priority, -req.admit_seq, slot)
                   for slot, req in enumerate(self._slot_req)
                   if req is not None]
        if not victims:
            raise OutOfBlocks(
                'block pool exhausted with no in-flight request to '
                'preempt — grow num_blocks')
        _, _, slot = min(victims)
        req = self._slot_req[slot]
        with _obs_trace.span('serve.preempt', cat='scheduler',
                             rid=req.rid, slot=slot,
                             generated=len(req.generated)):
            self._clear_slot(slot)
            req.state = 'preempted'
            self.preemption_count += 1
            req.mark('preempted')
            _obs.inc('serve.preemptions')
            self.queue.push(req)

    def _finish(self, slot, req):
        req.state = 'finished'
        req.mark('finished')
        _obs.inc('serve.finished')
        pad = self.eos_token_id if self.eos_token_id is not None else 0
        gen = (req.generated
               + [pad] * (req.max_new_tokens - len(req.generated)))
        self._results[req.rid] = np.concatenate(
            [req.prompt, np.asarray(gen, req.prompt.dtype)])
        self._clear_slot(slot)

    def _clear_slot(self, slot):
        self.allocator.free(self._slot_pages[slot])
        self._slot_req[slot] = None
        self._slot_pages[slot] = []
        self._btab[slot] = 0
        self._ctx[slot] = 0
        self._budget[slot] = 0
        self._dev = None


__all__ = ['ServingEngine', 'BlockAllocator', 'RequestQueue', 'Request',
           'OutOfBlocks']
