"""paddle_tpu.inference (ref: python/paddle/inference) — the Predictor
deployment API.

The reference's Predictor wraps the C++ AnalysisPredictor over a saved
inference model; here it wraps the StableHLO export the same
`save_inference_model` produces, executed by XLA. TensorRT/IR-pass
knobs on Config are accepted and recorded (XLA owns optimization).
Mixed precision needs no graph rewrite on TPU — the MXU computes fp32
matmuls with bf16 multiplicands natively — so
`convert_to_mixed_precision` is a relabeling copy (see its docstring).
"""
from __future__ import annotations

import numpy as np


class DataType:
    FLOAT32 = 0
    FLOAT16 = 1
    INT32 = 2
    INT64 = 3
    UINT8 = 4
    INT8 = 5
    BOOL = 6
    BFLOAT16 = 7


class PlaceType:
    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM = 3


class PrecisionType:
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


class XpuConfig:
    def __init__(self):
        self.device_id = 0


class Config:
    """ref: paddle.inference.Config(prog_file_or_prefix[, params_file])."""

    def __init__(self, prog_file=None, params_file=None):
        # accept either a path prefix (our artifact layout) or the
        # reference's (model, params) pair — strip known suffixes
        self._set_prefix(prog_file or '')
        self._use_accelerator = True
        self._precision = PrecisionType.Float32
        self._enabled_flags = {}

    def model_dir(self):
        import os

        return os.path.dirname(self._prefix)

    def _set_prefix(self, prefix):
        for suffix in ('.pdmodel', '.mlir', '.json'):
            if prefix.endswith(suffix):
                prefix = prefix[:-len(suffix)]
        self._prefix = prefix

    def set_model(self, model_path, params_path=None):
        """ref: Config.set_model — sets ONLY the path; accelerator /
        precision / pass flags the user already chose are preserved."""
        self._set_prefix(model_path or '')

    def set_prog_file(self, path):
        self._set_prefix(path or '')

    def set_params_file(self, path):
        pass  # params live beside the program under our prefix layout

    def prog_file(self):
        return self._prefix + '.mlir'

    def params_file(self):
        return self._prefix + '.pdiparams'

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision_mode=PrecisionType.Float32):
        self._use_accelerator = True
        self._precision = precision_mode

    def disable_gpu(self):
        self._use_accelerator = False

    def use_gpu(self):
        return self._use_accelerator

    def enable_memory_optim(self, *a):
        self._enabled_flags['memory_optim'] = True

    def enable_mkldnn(self):
        self._enabled_flags['mkldnn'] = True

    def switch_ir_optim(self, x=True):
        self._enabled_flags['ir_optim'] = x

    def enable_tensorrt_engine(self, *a, **k):
        # TensorRT is CUDA machinery; XLA compiles the same graph here
        self._enabled_flags['tensorrt_requested'] = True

    def set_cpu_math_library_num_threads(self, n):
        self._enabled_flags['cpu_threads'] = n

    def summary(self):
        return (f'Config(prefix={self._prefix!r}, '
                f'accelerator={self._use_accelerator}, '
                f'precision={self._precision})')


class Tensor:
    """ref: paddle.inference.Tensor — named IO handle on a Predictor."""

    def __init__(self, name, predictor, is_input):
        self._name = name
        self._predictor = predictor
        self._is_input = is_input

    def name(self):
        return self._name

    def copy_from_cpu(self, data):
        self._predictor._feeds[self._name] = np.asarray(data)

    def copy_to_cpu(self):
        return np.asarray(self._predictor._outputs[self._name])

    def reshape(self, shape):
        pass  # shapes come from the export; kept for API parity

    def shape(self):
        src = (self._predictor._feeds if self._is_input
               else self._predictor._outputs)
        v = src.get(self._name)
        return list(np.asarray(v).shape) if v is not None else []


class Predictor:
    """ref: paddle.inference.Predictor — run the exported program."""

    def __init__(self, config, _shared=None):
        import os

        from ..static import load_inference_model

        self._config = config
        if not config._prefix:
            raise ValueError(
                'Config has no model path: pass Config(path_prefix) or '
                'call config.set_model(path_prefix) before '
                'create_predictor')
        if not os.path.exists(config.prog_file()):
            raise FileNotFoundError(
                f'{config.prog_file()!r} not found — the prefix should '
                f'point at a save_inference_model/jit.save export')
        if _shared is not None:
            prog, feeds, fetches = _shared
        else:
            prog, feeds, fetches = load_inference_model(config._prefix)
        self._program = prog
        self._feed_names = list(feeds)
        self._fetch_names = list(fetches)
        self._feeds = {}
        self._outputs = {}

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def get_input_handle(self, name):
        return Tensor(name, self, True)

    def get_output_handle(self, name):
        return Tensor(name, self, False)

    def run(self, inputs=None):
        """Positional-list form returns outputs directly (new API);
        handle form stores them for copy_to_cpu (classic API)."""
        import jax.numpy as jnp

        if inputs is not None:
            args = [jnp.asarray(x) for x in inputs]
        else:
            args = [jnp.asarray(self._feeds[n]) for n in self._feed_names]
        # PrecisionType.Bfloat16/Half need no input cast: the exported
        # program's signature is fixed, and the TPU MXU already computes
        # fp32 matmuls with bf16 multiplicands — reduced precision is
        # the hardware default, not a graph rewrite
        out = self._program._fn(*args)
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        if len(outs) > len(self._fetch_names):
            # the export produced more outputs than declared names:
            # extend rather than silently dropping the tail
            base = self._fetch_names[-1] if self._fetch_names else 'out'
            self._fetch_names = self._fetch_names + [
                f'{base}_{i}' for i in range(1, len(outs)
                                             - len(self._fetch_names) + 1)]
        self._outputs = dict(zip(self._fetch_names, outs))
        return outs if inputs is not None else None

    def try_shrink_memory(self):
        pass

    def clear_intermediate_tensor(self):
        pass


def create_predictor(config):
    """ref: paddle.inference.create_predictor."""
    return Predictor(config)


class PredictorPool:
    """ref: paddle.inference.PredictorPool — N predictors over ONE
    loaded program (XLA executables are thread-safe, so the pool shares
    the artifact instead of parsing and holding the weights N times)."""

    def __init__(self, config, size=1):
        # build the first member normally so its path validation (clear
        # ValueError / FileNotFoundError) runs, then share its loaded
        # program with the rest
        first = Predictor(config)
        shared = (first._program, first._feed_names, first._fetch_names)
        self._preds = [first] + [Predictor(config, _shared=shared)
                                 for _ in range(max(1, size) - 1)]

    def retrieve(self, idx):
        return self._preds[idx % len(self._preds)]


def get_version():
    from ..version import full_version

    return f'paddle_tpu {full_version} (XLA inference)'


def get_trt_compile_version():
    return (0, 0, 0)   # no TensorRT in the XLA build


def get_trt_runtime_version():
    return (0, 0, 0)


def _get_phi_kernel_name(op_name):
    return op_name     # Phi is replaced by XLA; identity for tooling


def get_num_bytes_of_data_type(dtype):
    sizes = {DataType.FLOAT32: 4, DataType.FLOAT16: 2, DataType.INT32: 4,
             DataType.INT64: 8, DataType.UINT8: 1, DataType.INT8: 1,
             DataType.BOOL: 1, DataType.BFLOAT16: 2}
    return sizes[dtype]


# Compiled serving engine (persistent jit cache + KV donation +
# bucketed prefill) — the decode hot path; see engine.py.
from .engine import (  # noqa: E402
    COMPILE_CACHE,
    DecodeEngine,
    bucket_length,
    reset_trace_counts,
    total_traces,
    trace_counts,
)

# Continuous-batching scheduler over the paged KV block pool — the
# request-level serving path; see serving.py / docs/serving.md
# (resilience exceptions included: QueueFull is submit()'s load-shed
# signal, the Request* family is what result() raises for terminal
# non-success states).
from .serving import (  # noqa: E402
    BlockAllocator,
    InvalidSamplingParams,
    OutOfBlocks,
    QueueFull,
    RequestCancelled,
    RequestError,
    RequestExpired,
    RequestFailed,
    RequestQueue,
    ServingEngine,
)

# Disaggregated prefill/decode serving: a prefill-only engine that
# hands each request off at first token, the pair front that ferries
# int8 KV blobs between pools, and the process-boundary wire format;
# see disagg.py / docs/serving.md#disaggregated-serving.
from .disagg import (  # noqa: E402
    DisaggPair,
    PrefillEngine,
    pack_kv_blob,
    unpack_kv_blob,
)


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision=None,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """ref: paddle.inference.convert_to_mixed_precision.

    On TPU this is a relabeling copy, not a graph rewrite: the MXU
    already multiplies in bf16 for fp32 programs, so the exported
    StableHLO runs at mixed precision as-is. The copied artifacts gain a
    'precision' metadata tag purely as a record for tooling."""
    import json
    import os
    import shutil

    prefix = model_file
    for suffix in ('.pdmodel', '.mlir'):
        if prefix.endswith(suffix):
            prefix = prefix[:-len(suffix)]
    out_prefix = mixed_model_file
    for suffix in ('.pdmodel', '.mlir'):
        if out_prefix.endswith(suffix):
            out_prefix = out_prefix[:-len(suffix)]
    os.makedirs(os.path.dirname(os.path.abspath(out_prefix)), exist_ok=True)
    for ext in ('.mlir', '.pdiparams', '.pdmodel.json', '.pdmodel.txt'):
        src = prefix + ext
        if os.path.exists(src):
            shutil.copy(src, out_prefix + ext)
    meta_path = out_prefix + '.pdmodel.json'
    meta = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    names = {PrecisionType.Float32: 'float32', PrecisionType.Half: 'float16',
             PrecisionType.Int8: 'int8', PrecisionType.Bfloat16: 'bfloat16'}
    meta['precision'] = names.get(mixed_precision, 'bfloat16')
    with open(meta_path, 'w') as f:
        json.dump(meta, f)
    return out_prefix
