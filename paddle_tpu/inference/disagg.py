"""Disaggregated prefill/decode serving (docs/serving.md#disaggregated-
serving).

Chunked prefill stops one long prompt from monopolizing the engine,
but prefill and decode still SHARE one compute budget — a long-prompt
flood inflates decode p99 ITL because every scheduler step that runs a
chunk runs it ahead of the decode window in the same fused dispatch
(the interference DistServe quantifies, and that Mooncake/Splitwise
remove by running the two phases on separate pools). This module is
that split over the existing engine:

    prefill = PrefillEngine(model, tp=1, prefill_chunk=64, ...)
    decode  = ServingEngine(model, tp=1, phase_role='decode', ...)
    pair    = DisaggPair(prefill, decode)
    rid = pair.submit(prompt)
    pair.run()
    out = pair.result(rid)       # bit-equal to one monolithic engine

  - `PrefillEngine` is a ServingEngine (phase_role='prefill',
    decode_window=1) that only admits/chunks: the step a request's
    prefill completes also commits its FIRST token (the fused
    chunk+window dispatch), and the post-step handoff sweep exports
    its KV (`export_kv` — int8 pages + per-row scales ship
    bit-identical at ~half the bf16 bytes) and retires it locally as
    'migrated'. A draining prefill engine refuses new submissions
    (the inherited `submit` guard) while its sweep keeps completing
    in-flight handoffs.
  - `DisaggPair` routes submissions to the prefill pool, ferries each
    handoff blob into the decode pool (`import_kv` — retried while
    the pool is momentarily full, failed permanently only when the
    decode pool is idle-empty and still cannot fit it), and streams
    results from whichever pool finished the request (eos at the
    first token finishes ON the prefill engine).
  - `pack_kv_blob` / `unpack_kv_blob` flatten a blob to one
    self-describing byte string (JSON header + raw array bytes — no
    pickle), so a migration survives a process/host boundary the same
    way a snapshot does; the wire schema is `snapshot()`'s.

Bit-equality contract: a greedy stream served by the pair is
token-for-token the monolithic engine's (bf16 AND int8 pools) — the
export ships KV rows [0, context_len - 1) and the importer recomputes
the boundary position through the continuation-chunk machinery, so
both the migrated pages and the first decode logits are bit-identical
(bench.py's gate_serve_disagg pins it, with zero post-warmup compiles
on either pool).
"""
from __future__ import annotations

import base64
import json
import struct

import numpy as np

from ..observability import metrics as _obs
from ._schema import (PTKV_HEADER_MAGIC, PTKV_MAGIC, PTKV_VERSION,
                      SNAPSHOT_SCHEMA)
from .serving import OutOfBlocks, QueueFull, ServingEngine

__all__ = ['PrefillEngine', 'DisaggPair', 'pack_kv_blob',
           'unpack_kv_blob']

_MAGIC = PTKV_MAGIC


def pack_kv_blob(blob):
    """Flatten an `export_kv` blob into one byte string: a 4-byte
    magic, a length-prefixed JSON header (the blob minus its arrays,
    plus each array's group/layer/field/shape/dtype), then the raw
    array bytes in header order. No pickle — the wire format is
    inspectable and survives any same-endianness process boundary."""
    meta = {k: v for k, v in blob.items()
            if k not in ('layers', 'draft_layers')}
    specs, arrays = [], []
    for group in ('layers', 'draft_layers'):
        for li, lay in enumerate(blob.get(group) or []):
            for field in sorted(lay):
                a = np.ascontiguousarray(lay[field])
                specs.append({'group': group, 'layer': li,
                              'field': field, 'shape': list(a.shape),
                              'dtype': str(a.dtype)})
                arrays.append(a)
    head = json.dumps({'magic': PTKV_HEADER_MAGIC,
                       'version': PTKV_VERSION, 'meta': meta,
                       'arrays': specs}).encode('utf-8')
    out = [_MAGIC, struct.pack('<I', len(head)), head]
    out.extend(a.tobytes() for a in arrays)
    return b''.join(out)


def unpack_kv_blob(data):
    """Inverse of `pack_kv_blob`: bytes -> an `import_kv`-ready blob
    dict (arrays reconstructed zero-copy off the buffer).

    The whole layout is validated UP FRONT — preamble length, magic,
    header bounds, parseable header, and the byte-exact payload length
    the array specs imply — so a blob truncated or padded in transit
    fails here with the defect named, before `import_kv` sees it (and
    long before anything touches a block table)."""
    if len(data) < 8:
        raise ValueError(
            f'truncated KV migration blob: {len(data)} byte(s), need '
            f'at least 8 for the magic + header length')
    if data[:4] != _MAGIC:
        raise ValueError('not a packed KV migration blob (bad magic)')
    (hlen,) = struct.unpack_from('<I', data, 4)
    if 8 + hlen > len(data):
        raise ValueError(
            f'truncated KV migration blob: header claims {hlen} '
            f'byte(s) but only {len(data) - 8} follow the preamble')
    try:
        head = json.loads(data[8:8 + hlen].decode('utf-8'))
    except ValueError as e:
        raise ValueError(
            f'corrupt KV migration blob header: {e}') from None
    if head.get('magic') != PTKV_HEADER_MAGIC:
        raise ValueError(
            f"not a packed KV migration blob: {head.get('magic')!r}")
    if head.get('version') != PTKV_VERSION:
        raise ValueError(
            f"unsupported packed-blob version {head.get('version')!r} "
            f'(this reader unpacks version {PTKV_VERSION})')
    specs = head.get('arrays')
    if not isinstance(specs, list) or not isinstance(head.get('meta'),
                                                     dict):
        raise ValueError(
            'corrupt KV migration blob header: missing meta/arrays')

    def spec_dtype(spec):
        # jax registers bfloat16 & friends as numpy dtypes, so
        # np.dtype round-trips every pool dtype by name
        return np.dtype(spec['dtype']) if spec['dtype'] != 'bfloat16' \
            else _bf16()

    need = sum(int(np.prod(s['shape'])) * spec_dtype(s).itemsize
               for s in specs)
    if len(data) != 8 + hlen + need:
        raise ValueError(
            f'KV migration blob payload length mismatch: the header '
            f'specs need {need} byte(s), the buffer carries '
            f'{len(data) - 8 - hlen} — truncated or corrupted in '
            f'transit')
    blob = dict(head['meta'])
    off = 8 + hlen
    for spec in specs:
        dt = spec_dtype(spec)
        n = int(np.prod(spec['shape'])) * dt.itemsize
        a = np.frombuffer(data, dtype=dt, count=int(np.prod(spec['shape'])),
                          offset=off).reshape(spec['shape'])
        off += n
        group = blob.setdefault(spec['group'], [])
        while len(group) <= spec['layer']:
            group.append({})
        group[spec['layer']][spec['field']] = a
    for group in ('layers', 'draft_layers'):
        blob.setdefault(group, None)
    return blob


def _bf16():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


class PrefillEngine(ServingEngine):
    """A ServingEngine that only admits/chunks: every request hands
    off at its first committed token. decode_window defaults to 1 so
    a handed-off request carries exactly one generated token (the one
    the completing chunk's fused window produced) — the minimum that
    pins the next-step logits for the importer to verify against.

    Handoffs land in an internal list (`take_handoffs()`) or go
    straight to `handoff_sink` when one is given. A handed-off request
    leaves this engine's registries as state 'migrated' — `result()`
    on the DECODE engine (or the DisaggPair front) owns the outcome.
    """

    def __init__(self, model, decode_window=1, handoff_sink=None, **kw):
        kw.pop('phase_role', None)       # this class IS the role
        super().__init__(model, decode_window=decode_window,
                         phase_role='prefill', **kw)
        self.handoff_sink = handoff_sink
        self._handoffs: list = []

    def step(self):
        finished = super().step()
        self._sweep_handoffs()
        return finished

    def _sweep_handoffs(self):
        """Export + locally retire every slot whose prefill completed
        and committed at least one token. Runs AFTER the fused step
        (the commit loop already journaled the window), so the
        exported blob carries the request's full trail through its
        first token. Draining does not stop the sweep — a draining
        prefill engine refuses new submissions but completes every
        in-flight handoff."""
        for slot, req in enumerate(self._slot_req):
            if (req is None or self._pfill[slot] is not None
                    or not req.generated):
                continue
            req.mark('handoff', tokens=len(req.generated))
            blob = self.export_kv(req.rid)
            self._clear_slot(slot)
            self._live.pop(req.rid, None)
            if req.deadline is not None:
                self._deadlines_live -= 1
            req.state = 'migrated'
            self.migration_counts['handoffs'] += 1
            if _obs.enabled():
                _obs.inc('serve.handoffs')
            if self.handoff_sink is not None:
                self.handoff_sink(blob)
            else:
                self._handoffs.append(blob)
        self._update_gauges()

    def take_handoffs(self):
        """Drain and return the accumulated handoff blobs (empty when
        a `handoff_sink` consumes them at the sweep)."""
        out, self._handoffs = self._handoffs, []
        return out

    def snapshot(self):
        """The base snapshot plus the completed-but-unferried handoff
        blobs. A handed-off request has already LEFT this engine's
        registries (retired as 'migrated' at the sweep) — its exported
        blob sitting in `_handoffs` is the only record it exists, so a
        snapshot without it would silently drop the stream on a crash
        between sweep and ferry. Blobs ride packed + base64 so the
        snapshot stays one JSON-able dict (schema-1 compatible: the
        key is absent only from pre-handoff snapshots, and the base
        restore ignores keys it does not read)."""
        snap = super().snapshot()
        snap['handoffs'] = [
            base64.b64encode(pack_kv_blob(b)).decode('ascii')
            for b in self._handoffs]
        return snap

    def restore(self, snap):
        """Base restore, then re-materialize the unferried handoff
        blobs — `take_handoffs()` (or the DisaggPair ferry) picks them
        up exactly where the crashed engine left them."""
        report = super().restore(snap)
        for packed in snap.get('handoffs') or []:
            self._handoffs.append(
                unpack_kv_blob(base64.b64decode(packed)))
        report['handoffs'] = len(snap.get('handoffs') or [])
        return report


class DisaggPair:
    """The front over one prefill pool + one decode pool: submissions
    go to the prefill engine, handoff blobs ferry to the decode
    engine, results stream from whichever engine finished the request.
    Both engines must agree on the snapshot config (model structure +
    sampling contract) and the pool quantization world — checked at
    construction, so a mismatched pair fails fast instead of failing
    bit-equality.

    `step()` is one scheduler iteration across the pair: prefill
    step -> handoff sweep -> import retries -> decode step. A blob the
    decode pool cannot place yet (slots full, pool momentarily dry)
    waits in `pending_handoffs` and retries next step; it fails
    permanently only when the decode pool is EMPTY and still cannot
    fit it (nothing will ever free up) — `result(rid)` then re-raises
    the placement error.
    """

    def __init__(self, prefill, decode):
        if getattr(prefill, 'phase_role', None) != 'prefill':
            raise ValueError(
                "DisaggPair needs a prefill-role engine first "
                "(PrefillEngine, or ServingEngine(phase_role='prefill'))")
        if getattr(decode, 'phase_role', None) != 'decode':
            raise ValueError(
                "DisaggPair needs a decode-role engine second "
                "(ServingEngine(phase_role='decode'))")
        pc, dc = prefill._snapshot_config(), decode._snapshot_config()
        diff = sorted(k for k in pc if dc.get(k) != pc[k])
        if diff:
            raise ValueError(
                f'prefill/decode engines disagree on {diff} — a pair '
                f'must share the snapshot config for migrated streams '
                f'to stay bit-equal')
        if prefill.kv_cache_dtype != decode.kv_cache_dtype:
            raise ValueError(
                'prefill/decode engines disagree on kv_cache_dtype — '
                'blobs do not cross quantization worlds')
        if (prefill.draft is None) != (decode.draft is None):
            raise ValueError(
                'prefill/decode engines disagree on speculative '
                'decoding (draft=...) — a blob without draft KV cannot '
                'feed a speculative decode pool')
        self.prefill = prefill
        self.decode = decode
        self._pending: list = []      # blobs awaiting decode-pool room
        self._failed: dict = {}       # rid -> placement error

    # -- the serving surface ------------------------------------------------

    def submit(self, prompt, **kw):
        return self.prefill.submit(prompt, **kw)

    def step(self):
        """One iteration across the pair; returns finished Requests
        from both pools (prefill-finished = eos/budget at the very
        first token — those never migrate)."""
        finished = list(self.prefill.step())
        self._pending.extend(self.prefill.take_handoffs())
        self._flush_pending()
        finished.extend(self.decode.step())
        return finished

    def _flush_pending(self):
        still = []
        for blob in self._pending:
            rid = int(blob['request']['rid'])
            try:
                self.decode.import_kv(rid, blob)
            except (QueueFull, OutOfBlocks) as e:
                if (self.decode.in_flight() == 0
                        and not len(self.decode.queue)):
                    # nothing in the decode pool will ever free up —
                    # retrying forever would wedge run(); surface the
                    # placement error at result(rid)
                    self._failed[rid] = e
                else:
                    still.append(blob)
        self._pending = still

    def run(self, max_steps=None):
        """Step until both pools and the handoff queue drain."""
        steps = 0
        while (len(self.prefill.queue) or self.prefill.in_flight()
               or self._pending or len(self.decode.queue)
               or self.decode.in_flight()):
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return steps

    def serve(self, prompts, max_new_tokens=None):
        """Submit + run + collect, preserving submission order (the
        monolithic `serve()` convenience over the pair)."""
        rids = [self.submit(p, max_new_tokens=max_new_tokens)
                for p in prompts]
        self.run()
        return [self.result(rid) for rid in rids]

    def result(self, rid):
        """Terminal outcome from whichever pool owns it (decode first
        — that is where migrated requests finish). An import that
        failed permanently re-raises its placement error here."""
        if rid in self._failed:
            raise self._failed.pop(rid)
        try:
            return self.decode.result(rid)
        except KeyError:
            return self.prefill.result(rid)

    def status(self, rid):
        for blob in self._pending:
            if int(blob['request']['rid']) == rid:
                return 'migrating'
        try:
            return self.decode.status(rid)
        except KeyError:
            return self.prefill.status(rid)

    def in_flight(self):
        return (self.prefill.in_flight() + self.decode.in_flight()
                + len(self._pending))

    def stats(self):
        return {'prefill': self.prefill.stats(),
                'decode': self.decode.stats(),
                'pending_handoffs': len(self._pending)}

    def drain(self, on=True):
        """Flip BOTH engines' drain flags (new submissions refused;
        in-flight work — including pending handoffs — completes)."""
        self.prefill.draining = bool(on)
        self.decode.draining = bool(on)

    # -- crash-safe warm restart across the pair ---------------------------

    def snapshot(self):
        """Both pools' snapshots plus the ferry state BETWEEN them:
        blobs awaiting decode-pool room (packed + base64, like the
        prefill engine's own unferried handoffs) and the permanently
        failed placements. Without the ferry section, a crash between
        handoff and import silently drops every in-transit stream —
        neither pool's snapshot knows it exists."""
        return {
            'schema': SNAPSHOT_SCHEMA,
            'prefill': self.prefill.snapshot(),
            'decode': self.decode.snapshot(),
            'pending': [
                base64.b64encode(pack_kv_blob(b)).decode('ascii')
                for b in self._pending],
            'failed': {str(rid): repr(e)
                       for rid, e in self._failed.items()},
        }

    def restore(self, snap):
        """Load a pair `snapshot()` into a FRESH pair (both engines
        fresh — the per-engine restores enforce it). In-transit blobs
        resume ferrying on the next step; failed placements re-raise
        at `result(rid)` (as RuntimeError carrying the original
        error's repr — the exception OBJECT does not cross a process
        boundary). Raises ValueError naming missing keys or any
        per-engine config mismatch. Returns a report dict."""
        if snap.get('schema') != SNAPSHOT_SCHEMA:
            raise ValueError(
                f"unsupported pair snapshot schema "
                f"{snap.get('schema')!r} (this pair reads schema "
                f'{SNAPSHOT_SCHEMA})')
        missing = sorted(k for k in ('prefill', 'decode')
                         if k not in snap)
        if missing:
            raise ValueError(
                f'pair snapshot missing required key(s) {missing}: '
                f'not a DisaggPair.snapshot() dict')
        report = {'prefill': self.prefill.restore(snap['prefill']),
                  'decode': self.decode.restore(snap['decode'])}
        self._pending = [
            unpack_kv_blob(base64.b64decode(packed))
            for packed in snap.get('pending') or []]
        self._failed = {int(rid): RuntimeError(msg)
                        for rid, msg in (snap.get('failed')
                                         or {}).items()}
        report['pending'] = len(self._pending)
        report['failed'] = len(self._failed)
        return report

    def close(self):
        self.prefill.close()
        self.decode.close()
