"""Profiler (ref: python/paddle/profiler/profiler.py).

Wraps `jax.profiler`: traces go to TensorBoard-compatible files; the
same RecordEvent/Profiler surface as the reference, with XLA's own
per-op timeline replacing Paddle's host/device event collation.
"""
from __future__ import annotations

import contextlib
import os
import time

import jax

__all__ = ['Profiler', 'RecordEvent', 'ProfilerTarget', 'profile',
           'start_profiler', 'stop_profiler', 'StepTimer']


class ProfilerTarget:
    CPU = 'cpu'
    GPU = 'gpu'
    TPU = 'tpu'
    CUSTOM_DEVICE = 'custom'


class RecordEvent:
    """ref: paddle.profiler.RecordEvent — named trace annotation.

    Also usable as a decorator. Lowers to jax.profiler.TraceAnnotation,
    which shows up on the XLA timeline.
    """

    def __init__(self, name, event_type=None):
        self.name = name
        self._ctx = None

    def begin(self):
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapped(*a, **kw):
            with jax.profiler.TraceAnnotation(self.name):
                return fn(*a, **kw)

        return wrapped


class Profiler:
    """ref: paddle.profiler.Profiler.

    with Profiler(on_trace_ready=...) as p:
        for batch in loader:
            train_step(...)
            p.step()
    """

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 log_dir='./profiler_log', timer_only=False, **kw):
        self.log_dir = log_dir
        self.timer_only = timer_only
        self.on_trace_ready = on_trace_ready
        self._running = False
        self._step_times = []
        self._t_last = None

    def start(self):
        if not self.timer_only:
            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
        self._running = True
        self._t_last = time.perf_counter()
        return self

    def stop(self):
        if self._running and not self.timer_only:
            jax.profiler.stop_trace()
        self._running = False
        if self.on_trace_ready:
            self.on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._t_last is not None:
            self._step_times.append(now - self._t_last)
        self._t_last = now

    def step_info(self, unit=None):
        if not self._step_times:
            return 'no steps recorded'
        import numpy as np

        t = np.asarray(self._step_times)
        return (f'steps={len(t)} avg={t.mean() * 1e3:.2f}ms '
                f'p50={np.percentile(t, 50) * 1e3:.2f}ms '
                f'p99={np.percentile(t, 99) * 1e3:.2f}ms')

    def summary(self, **kw):
        print(self.step_info())

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


@contextlib.contextmanager
def profile(log_dir='./profiler_log'):
    p = Profiler(log_dir=log_dir).start()
    try:
        yield p
    finally:
        p.stop()


_global_profiler = None


def start_profiler(log_dir='./profiler_log', **kw):
    global _global_profiler
    _global_profiler = Profiler(log_dir=log_dir, **kw).start()


def stop_profiler():
    global _global_profiler
    if _global_profiler is not None:
        _global_profiler.stop()
        _global_profiler = None


class StepTimer:
    """Lightweight step timing (timer_only Profiler convenience)."""

    def __init__(self):
        self._p = Profiler(timer_only=True).start()

    def step(self):
        self._p.step()

    def info(self):
        return self._p.step_info()


class ProfilerState:
    """ref: paddle.profiler.ProfilerState."""

    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SortedKeys:
    """ref: paddle.profiler.SortedKeys (summary ordering)."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView:
    """ref: paddle.profiler.SummaryView."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """ref: paddle.profiler.make_scheduler — step -> ProfilerState
    callable driving window-based capture."""
    cycle = closed + ready + record

    def schedule(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def export_chrome_tracing(dir_name, worker_name=None):
    """ref: paddle.profiler.export_chrome_tracing — on_trace_ready
    callback. jax.profiler already writes TensorBoard/Perfetto traces
    into the profiler's log dir; this returns a callback that records
    where."""

    def handler(prof):
        prof.exported_to = dir_name
        return dir_name

    return handler


def export_protobuf(dir_name, worker_name=None):
    """ref: paddle.profiler.export_protobuf — same artifact family
    (jax traces are already protobuf-based under the hood)."""
    return export_chrome_tracing(dir_name, worker_name)


def load_profiler_result(filename):
    """ref: paddle.profiler.load_profiler_result — load an exported
    chrome trace JSON for programmatic inspection."""
    import gzip
    import json

    opener = gzip.open if str(filename).endswith('.gz') else open
    with opener(filename, 'rt') as f:
        return json.load(f)


__all__ += ['ProfilerState', 'SortedKeys', 'SummaryView', 'make_scheduler',
            'export_chrome_tracing', 'export_protobuf',
            'load_profiler_result']
