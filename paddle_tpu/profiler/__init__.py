"""Profiler (ref: python/paddle/profiler/profiler.py).

Wraps `jax.profiler`: traces go to TensorBoard-compatible files; the
same RecordEvent/Profiler surface as the reference, with XLA's own
per-op timeline replacing Paddle's host/device event collation.
"""
from __future__ import annotations

import contextlib
import os
import time

import jax

__all__ = ['Profiler', 'RecordEvent', 'ProfilerTarget', 'profile',
           'start_profiler', 'stop_profiler', 'StepTimer']


class ProfilerTarget:
    CPU = 'cpu'
    GPU = 'gpu'
    TPU = 'tpu'
    CUSTOM_DEVICE = 'custom'


class RecordEvent:
    """ref: paddle.profiler.RecordEvent — named trace annotation.

    Also usable as a decorator. ONE API, BOTH timelines: lowers to
    jax.profiler.TraceAnnotation (the XLA/TensorBoard device timeline)
    AND records a host span in observability's tracer (the Perfetto
    host_trace.json), so the same name lines the two traces up — the
    reference's host/device event collation, rebuilt on the two
    recorders this stack actually has.
    """

    def __init__(self, name, event_type=None):
        self.name = name
        self._ctx = None
        self._span = None

    def begin(self):
        from ..observability import tracing as _tracing

        self._span = _tracing.span(self.name, cat='record_event').begin()
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None
        if self._span is not None:
            self._span.end()
            self._span = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def __call__(self, fn):
        import functools

        from ..observability import tracing as _tracing

        @functools.wraps(fn)
        def wrapped(*a, **kw):
            # annotate() is the same dual-timeline bridge in context-
            # manager form (TraceAnnotation + host span)
            with _tracing.annotate(self.name, cat='record_event'):
                return fn(*a, **kw)

        return wrapped


class Profiler:
    """ref: paddle.profiler.Profiler.

    with Profiler(on_trace_ready=...) as p:
        for batch in loader:
            train_step(...)
            p.step()
    """

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 log_dir='./profiler_log', timer_only=False, **kw):
        self.log_dir = log_dir
        self.timer_only = timer_only
        self.on_trace_ready = on_trace_ready
        self._running = False
        self._step_times = []
        self._t_last = None

    def start(self):
        if not self.timer_only:
            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
        self._running = True
        self._t_last = time.perf_counter()
        return self

    def stop(self):
        if self._running and not self.timer_only:
            jax.profiler.stop_trace()
            # drop the host-side span trace next to jax's device trace:
            # one log_dir holds both halves of the timeline
            # any failure here (unwritable log_dir, import oddity) must
            # cost only the host-trace artifact, never break stop():
            # the device trace is already closed and on_trace_ready
            # still has to fire
            try:
                from ..observability import tracing as _tracing

                _tracing.export(os.path.join(self.log_dir,
                                             'host_trace.json'))
            except Exception:  # noqa: BLE001 - artifact is best-effort
                pass
        self._running = False
        if self.on_trace_ready:
            self.on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._t_last is not None:
            self._step_times.append(now - self._t_last)
        self._t_last = now

    def step_info(self, unit=None):
        if not self._step_times:
            return 'no steps recorded'
        import numpy as np

        t = np.asarray(self._step_times)
        return (f'steps={len(t)} avg={t.mean() * 1e3:.2f}ms '
                f'p50={np.percentile(t, 50) * 1e3:.2f}ms '
                f'p99={np.percentile(t, 99) * 1e3:.2f}ms')

    def summary(self, sorted_by=None, views=None, **kw):
        """Formatted step-timing report (ref profiler.py summary tables;
        per-op device timing lives in the exported trace — use
        `profiler.op_summary(fn, *args)` for the compile-time view)."""
        if not self._step_times:
            print('no steps recorded')
            return
        import numpy as np

        t = np.asarray(self._step_times) * 1e3
        rows = [
            ('steps', f'{len(t)}'),
            ('avg', f'{t.mean():.2f} ms'),
            ('p50', f'{np.percentile(t, 50):.2f} ms'),
            ('p90', f'{np.percentile(t, 90):.2f} ms'),
            ('p99', f'{np.percentile(t, 99):.2f} ms'),
            ('min', f'{t.min():.2f} ms'),
            ('max', f'{t.max():.2f} ms'),
            ('total', f'{t.sum():.2f} ms'),
        ]
        w = max(len(k) for k, _ in rows)
        sep = '-' * (w + 14)
        print(sep)
        print(f'{"step timing":<{w + 2}}')
        print(sep)
        for k, v in rows:
            print(f'{k:<{w + 2}}{v}')
        print(sep)
        if not self.timer_only:
            print(f'device trace: {self.log_dir} (TensorBoard / Perfetto)')

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


@contextlib.contextmanager
def profile(log_dir='./profiler_log'):
    p = Profiler(log_dir=log_dir).start()
    try:
        yield p
    finally:
        p.stop()


_global_profiler = None


def start_profiler(log_dir='./profiler_log', **kw):
    global _global_profiler
    _global_profiler = Profiler(log_dir=log_dir, **kw).start()


def stop_profiler():
    global _global_profiler
    if _global_profiler is not None:
        _global_profiler.stop()
        _global_profiler = None


class StepTimer:
    """Lightweight step timing (timer_only Profiler convenience)."""

    def __init__(self):
        self._p = Profiler(timer_only=True).start()

    def step(self):
        self._p.step()

    def info(self):
        return self._p.step_info()


class ProfilerState:
    """ref: paddle.profiler.ProfilerState."""

    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SortedKeys:
    """ref: paddle.profiler.SortedKeys (summary ordering)."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView:
    """ref: paddle.profiler.SummaryView."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """ref: paddle.profiler.make_scheduler — step -> ProfilerState
    callable driving window-based capture."""
    cycle = closed + ready + record

    def schedule(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def export_chrome_tracing(dir_name, worker_name=None):
    """ref: paddle.profiler.export_chrome_tracing — on_trace_ready
    callback. jax.profiler already writes TensorBoard/Perfetto traces
    into the profiler's log dir; this returns a callback that records
    where."""

    def handler(prof):
        prof.exported_to = dir_name
        return dir_name

    return handler


def export_protobuf(dir_name, worker_name=None):
    """ref: paddle.profiler.export_protobuf — same artifact family
    (jax traces are already protobuf-based under the hood)."""
    return export_chrome_tracing(dir_name, worker_name)


def op_summary(fn, *args, print_table=True, top=20, **kwargs):
    """Per-op report for a jittable function (the reference's operator/
    kernel summary views, rebuilt on XLA's compile-time analyses).

    Compiles `fn(*args)` and reports: opcode histogram of the optimized
    HLO (what XLA actually runs, post-fusion), total FLOPs and bytes
    from `cost_analysis`, and the memory footprint split from
    `memory_analysis`. Returns the stats dict (also printed as a table
    unless print_table=False).
    """
    import collections
    import re

    import jax as _jax

    # tracelint: disable=TL001 - one-shot profiling compile, not served
    compiled = _jax.jit(fn).lower(*args, **kwargs).compile()
    hist = collections.Counter()
    for mod in compiled.as_text().splitlines():
        m = re.search(r'=\s+[\w\[\],{}() ]*?\s*([a-z][\w-]*)\(', mod)
        if m and not mod.lstrip().startswith(('ROOT', '//')):
            hist[m.group(1)] += 1
        elif mod.lstrip().startswith('ROOT'):
            m = re.search(r'=\s+\S+\s+([a-z][\w-]*)\(', mod)
            if m:
                hist[m.group(1)] += 1
    # cost/memory quirks (list-vs-dict, raising backends) are handled
    # ONCE in observability.costs — the same normalized reading the AOT
    # manifest cost stamps and the live MFU gauges use
    from ..observability.costs import analyze

    cost = analyze(compiled)
    mem_stats = cost['memory']
    stats = {
        'opcode_histogram': dict(hist.most_common()),
        'flops': cost['flops'],
        'bytes_accessed': cost['bytes_accessed'],
        'memory': mem_stats,
    }
    if print_table:
        print('-' * 44)
        print(f'{"opcode":<28}{"count":>8}')
        print('-' * 44)
        for op, n in hist.most_common(top):
            print(f'{op:<28}{n:>8}')
        print('-' * 44)
        if stats['flops']:
            print(f'{"total flops":<28}{stats["flops"]:>14.3e}')
        if stats['bytes_accessed']:
            print(f'{"bytes accessed":<28}{stats["bytes_accessed"]:>14.3e}')
        for k, v in mem_stats.items():
            print(f'{k:<28}{v:>14,}')
        print('-' * 44)
    return stats


def load_profiler_result(filename):
    """ref: paddle.profiler.load_profiler_result — load an exported
    chrome trace JSON for programmatic inspection."""
    import gzip
    import json

    opener = gzip.open if str(filename).endswith('.gz') else open
    with opener(filename, 'rt') as f:
        return json.load(f)


__all__ += ['ProfilerState', 'SortedKeys', 'SummaryView', 'make_scheduler', 'op_summary',
            'export_chrome_tracing', 'export_protobuf',
            'load_profiler_result']
