"""Training runtime: the compiled train-side twin of `inference/`.

`TrainEngine` owns the training hot path end to end — one donated,
module-level-jitted fused step (fwd + bwd + optimizer update), gradient
accumulation as a `lax.scan` over microbatches inside that single
dispatch, the lr schedule and AMP loss scaling folded into the trace,
and metrics accumulated on device with ONE host sync per log window.
See docs/train_engine.md for the contract.
"""
from .engine import (  # noqa: F401
    TRAIN_COMPILE_CACHE,
    TrainEngine,
    reset_trace_counts,
    total_traces,
    trace_counts,
)

__all__ = [
    'TrainEngine', 'TRAIN_COMPILE_CACHE', 'trace_counts', 'total_traces',
    'reset_trace_counts',
]
