"""TrainEngine — the compiled training hot path (the training-side twin
of inference/engine.py's DecodeEngine).

Why an engine instead of hapi's per-Model jitted closure: the hapi loop
host-synced `float(loss)` on EVERY step, host-computed the lr schedule
each iteration (including a device readback of the step counter), never
donated the params or optimizer state (a full copy of both per step),
and ran one update per loader batch with no way to accumulate. This
module owns the train step end to end:

  1. Persistent compiled-function cache. The fused step lives at MODULE
     level, so jax's trace cache is keyed on (optimizer, loss, model
     pytree structure, batch shapes, static config) and survives across
     engines and fit() calls. `trace_counts()` exposes a per-function
     retrace counter so steady-state training can be ASSERTED to be 0
     retraces (bench.py and tests/test_train_engine.py do).

  2. Buffer donation. The params, the optimizer state, and the AMP
     scaler state are donated (`donate_argnames`), so XLA updates them
     IN PLACE instead of allocating a second copy of the model + two
     Adam moments every step. Contract: a (model, opt_state) passed to
     `step()` is dead to the caller — read the new ones back off the
     engine.

  3. Gradient accumulation inside the dispatch. `accum_steps=k` splits
     the global batch into k microbatches and runs them as a `lax.scan`
     INSIDE the one compiled step — grads accumulate in fp32 on device,
     the optimizer applies ONE update per global batch, and the whole
     thing is still a single dispatch with no host round trip between
     microbatches. Mean-of-micro-means equals the fused full-batch
     loss/grads (equal micro sizes), so k is a pure memory knob.

  4. The lr schedule and AMP loss scale are traced. A traceable
     LRScheduler is evaluated from the DEVICE step counter inside the
     compiled step (no host work at all); a plain float lr rides in as
     a traced scalar argument (so `set_lr` still takes effect without a
     retrace); only host-only schedulers (ReduceOnPlateau — metric
     driven by construction) fall back to a host-computed traced
     argument. fp16 dynamic loss scaling runs entirely on device:
     scale/unscale, the non-finite check, the skip-update select, and
     the scale growth/backoff are all inside the trace.

  5. Windowed metric sync. `step()` returns nothing for
     `log_window - 1` out of every `log_window` calls; losses, preds
     and labels stay on device in a pending buffer and `sync()` fetches
     the WHOLE window with one `jax.device_get` (mirroring the decode
     engine's `_commit_window` contract: one host sync per window,
     never per step).

Input side: `prefetch(iterator)` wraps io.dataloader.prefetch_to_device
with a mesh-aware batch sharding (distributed.sharding.data_sharding),
so H2D DMA of the next global batch overlaps the current step's compute
and dp/fsdp shards land directly on their devices.
"""
from __future__ import annotations

import collections
import functools
import inspect
import itertools
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tree import split_trainable
from ..inference.engine import CompileCache, model_struct, model_tag
from ..observability import journal as _journal
from ..observability import metrics as _obs
from ..observability import timeseries as _obs_ts
from ..observability import tracing as _obs_trace

# ---------------------------------------------------------------------------
# Compile accounting (the training twin of inference.engine's counters)
# ---------------------------------------------------------------------------

_TRACE_COUNTS: collections.Counter = collections.Counter()


def _count_trace(name):
    """Called from INSIDE to-be-jitted python bodies: runs only while
    tracing, so the counter is exactly the number of (re)compilations.
    Also ticks the shared `compile.traces` registry counter and drops a
    `trace:<name>` instant on the host trace (the same compile/retrace
    accounting the inference engines feed)."""
    _TRACE_COUNTS[name] += 1
    _obs.inc('compile.traces')
    _obs_trace.compile_event(f'trace:{name}')
    _journal.record('trace', fn=name)


def trace_counts():
    return dict(_TRACE_COUNTS)


def total_traces():
    return sum(_TRACE_COUNTS.values())


def reset_trace_counts():
    _TRACE_COUNTS.clear()


# the engine-level compilation-key registry, same bookkeeping class the
# decode engine uses (hits/misses observable, tests assert steady state)
TRAIN_COMPILE_CACHE = CompileCache()

# monotonic ENGINE ids for the registry key. Unlike the decode engine,
# the model cannot carry the id: stamping an attribute on a Layer
# changes its pytree static structure (Layer aux data is the __dict__),
# which would break tree-maps against pre-stamp trees — and the model
# OBJECT is replaced by every donated step anyway. The engine instance
# is the stable identity on the training side.
_ENGINE_IDS = itertools.count()


# ---------------------------------------------------------------------------
# Module-level compiled steps (the persistent jit cache)
# ---------------------------------------------------------------------------

def _compute_loss(model, inputs, labels, loss_fn, loss_mode):
    """The one forward contract: 'fn' -> preds = model(*inputs), loss =
    loss_fn(preds, *labels) (the hapi shape); 'model' -> the model owns
    its loss (LlamaForCausalLM.loss — the bench shape); 'none' -> preds
    only (eval without a loss)."""
    if loss_mode == 'model':
        return model.loss(*inputs, *labels), ()
    preds = model(*inputs)
    if loss_mode == 'none' or loss_fn is None:
        return jnp.zeros((), jnp.float32), preds
    return loss_fn(preds, *labels), preds


def _zeros_like_grads(model):
    """fp32 accumulator tree shaped like the trainable partition (None
    leaves align with frozen slots, as value_and_grad returns them)."""
    t, _ = split_trainable(model)
    return jax.tree.map(
        lambda p: None if p is None else jnp.zeros(p.shape, jnp.float32),
        t, is_leaf=lambda x: x is None)


@functools.partial(
    jax.jit,
    donate_argnames=('model', 'opt_state', 'scaler_state'),
    static_argnames=('opt', 'loss_fn', 'loss_mode', 'accum', 'lr_mode',
                     'scaler_cfg', 'with_preds'))
def _fused_train_step(model, opt_state, scaler_state, inputs, labels,
                      host_lr, *, opt, loss_fn, loss_mode, accum, lr_mode,
                      scaler_cfg, with_preds):
    """ONE dispatch per global batch: scan over `accum` microbatches
    (grads accumulated in fp32 on device), one optimizer update, lr and
    loss scale resolved inside the trace. Params, optimizer state and
    scaler state are donated — updated in place, never copied."""
    from .. import autograd

    _count_trace('train_step')
    if lr_mode == 'traced':
        # schedule math lives on device, keyed by the DEVICE step
        # counter — no host work, no readback, no retrace
        lr = opt.get_lr(opt_state['step'] + 1)
    else:
        lr = host_lr                       # traced scalar arg (or unused)
    scale = (scaler_state['scale'] if scaler_state is not None
             else jnp.ones((), jnp.float32))

    def scaled_loss(m, x, y):
        loss, preds = _compute_loss(m, x, y, loss_fn, loss_mode)
        # the forward may update layer state in place on the traced copy
        # (BatchNorm running stats): carry the mutated model out via aux
        # so the update lands in the returned pytree
        return loss * scale.astype(loss.dtype), (m, loss, preds)

    vg = autograd.value_and_grad(scaled_loss, has_aux=True)

    if accum == 1:
        (_, (model, loss, preds)), grads = vg(model, inputs, labels)
        if not with_preds:
            # drop preds from the jit OUTPUTS: a returned value cannot
            # be DCE'd, and the [B, S, V] logits of an LM step are real
            # HBM when nobody consumes them
            preds = ()
    else:
        micro = jax.tree.map(
            lambda a: a.reshape((accum, a.shape[0] // accum) + a.shape[1:]),
            (inputs, labels))

        def body(carry, mb):
            m, gsum = carry
            x, y = mb
            # grads w.r.t. the carried model: its TRAINABLE leaves are
            # the originals (only buffers evolve across microbatches)
            (_, (m, mloss, mpreds)), g = vg(m, x, y)
            gsum = jax.tree.map(
                lambda s, gg: None if s is None else s + gg.astype(s.dtype),
                gsum, g, is_leaf=lambda v: v is None)
            return (m, gsum), (mloss, mpreds if with_preds else ())

        (model, gsum), (losses, mpreds) = jax.lax.scan(
            body, (model, _zeros_like_grads(model)), micro)
        grads = jax.tree.map(
            lambda s: None if s is None else s / accum,
            gsum, is_leaf=lambda v: v is None)
        loss = jnp.mean(losses)
        # (k, B/k, ...) microbatch outputs fold back to the global batch
        preds = jax.tree.map(
            lambda a: a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:]),
            mpreds) if with_preds else ()

    new_scaler_state = scaler_state
    if scaler_state is not None:
        inv = 1.0 / scale
        grads = jax.tree.map(
            lambda g: None if g is None else g * inv.astype(g.dtype),
            grads, is_leaf=lambda v: v is None)
        found_inf = jnp.zeros((), bool)
        for g in jax.tree.leaves(grads):
            found_inf = found_inf | jnp.any(
                ~jnp.isfinite(g.astype(jnp.float32)))
    else:
        found_inf = None

    if lr_mode == 'none':
        new_model, new_state = opt.apply_gradients(model, grads, opt_state)
    else:
        new_model, new_state = opt.apply_gradients(model, grads, opt_state,
                                                   lr=lr)

    if found_inf is not None:
        # non-finite grads: keep the old params/state (the update is a
        # no-op select on device — no host involvement in the skip)
        keep = lambda old, new: jax.tree.map(  # noqa: E731
            lambda o, n: o if o is None else jnp.where(found_inf, o, n),
            old, new, is_leaf=lambda v: v is None)
        new_model = keep(model, new_model)
        new_state = keep(opt_state, new_state)
        incr_ratio, decr_ratio, incr_every = scaler_cfg
        good = jnp.where(found_inf, 0, scaler_state['good'] + 1)
        scale = jnp.where(
            found_inf,
            jnp.maximum(scale * decr_ratio, 1.0),
            jnp.where(good >= incr_every, scale * incr_ratio, scale))
        good = jnp.where(good >= incr_every, 0, good)
        new_scaler_state = {'scale': scale, 'good': good}

    return new_model, new_state, new_scaler_state, loss, preds


@functools.partial(jax.jit,
                   static_argnames=('loss_fn', 'loss_mode', 'with_preds'))
def _eval_step(model, inputs, labels, *, loss_fn, loss_mode, with_preds):
    _count_trace('eval_step')
    loss, preds = _compute_loss(model, inputs, labels, loss_fn, loss_mode)
    return loss, (preds if with_preds else ())


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

def _to_tuple(x):
    if x is None:
        return ()
    return tuple(x) if isinstance(x, (list, tuple)) else (x,)


def _callable_tag(fn):
    """Serializable identity for a loss callable: qualified name plus
    a hash over bytecode, constants, AND closure cell values — two
    different lambdas (both '<lambda>'), same bytecode with different
    constants (`* 0.5` vs `* 0.7`), or factory-made closures over
    different values all compile different HLO and must not share an
    AOT artifact config hash."""
    if fn is None:
        return None
    name = (f'{getattr(fn, "__module__", "?")}.'
            f'{getattr(fn, "__qualname__", type(fn).__qualname__)}')
    code = getattr(fn, '__code__', None)
    if code is not None:
        import hashlib

        h = hashlib.sha256(code.co_code)
        h.update(repr(code.co_consts).encode())
        for cell in (getattr(fn, '__closure__', None) or ()):
            try:
                h.update(repr(cell.cell_contents).encode())
            except ValueError:       # empty cell
                pass
        name += ':' + h.hexdigest()[:8]
    return name


class TrainEngine:
    """Owns the compiled train/eval path for one (model, optimizer,
    loss) triple.

        eng = TrainEngine(model, optimizer, loss_fn=loss, metrics=[acc],
                          accum_steps=4, log_window=10)
        for batch in eng.prefetch(loader):
            logs = eng.step(inputs, labels)   # None until the window
            if logs is not None:              # closes — ONE device_get
                print(logs['loss'])           # per log_window steps
        logs = eng.sync()                     # flush the tail

    Contract (docs/train_engine.md):
      - `eng.model` / `eng.opt_state` are the live pytrees; the ones you
        passed in (and every pre-step snapshot) are DONATED — dead after
        the next step().
      - exactly one jit trace per (batch shape, static config); steady
        state is 0 retraces (`total_traces()` is the proof).
      - at most one host sync per `log_window` steps; `step()` itself
        never blocks on the device.
      - `accum_steps=k` requires the global batch divisible by k and
        matches the fused full-batch update within float tolerance.

    `loss_fn=None` uses `model.loss(*inputs)` (the Llama pretrain
    shape); otherwise hapi's `loss_fn(model(*inputs), *labels)`.
    `optimizer=None` builds an eval-only engine (hapi uses this when
    prepare() got no optimizer).
    """

    def __init__(self, model, optimizer=None, loss_fn=None, *,
                 accum_steps=1, scaler=None, metrics=(), log_window=10,
                 mesh=None, opt_state=None, loss_mode=None):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.accum_steps = int(accum_steps)
        if self.accum_steps < 1:
            raise ValueError(
                f'accum_steps must be >= 1, got {self.accum_steps}')
        self.metrics = list(metrics)
        self.log_window = max(1, int(log_window))
        self.mesh = mesh
        if loss_mode is None:
            loss_mode = 'fn' if loss_fn is not None else 'model'
        self.loss_mode = loss_mode
        self._engine_id = next(_ENGINE_IDS)
        self.opt_state = None
        if optimizer is not None:
            self.opt_state = (opt_state if opt_state is not None
                              else optimizer.init(model))
        # lr threading: does apply_gradients accept a traced lr at all?
        self._lr_kw = False
        if optimizer is not None:
            try:
                params = inspect.signature(
                    optimizer.apply_gradients).parameters
                self._lr_kw = 'lr' in params and hasattr(optimizer, 'get_lr')
            except (TypeError, ValueError):
                pass
        # AMP: fp16 dynamic loss scaling folds into the trace; bf16
        # scalers are disabled (scale 1) and cost nothing
        self.scaler = scaler
        self.scaler_state = None
        self._scaler_cfg = None
        if scaler is not None and scaler.is_enable():
            self._scaler_cfg = (float(scaler.incr_ratio),
                                float(scaler.decr_ratio),
                                int(scaler.incr_every_n_steps))
            self.scaler_state = scaler.state()
        self._host_step = 0
        self._pending = []              # train window: (loss, preds, labels)
        self._eval_pending = []
        self._last_vals = None
        self._last_loss = None
        # telemetry window accounting (host wall clock + input-element
        # counts, rolled into the registry at each sync — the window
        # boundary is the ONLY place train metrics are recorded, so
        # instrumentation inherits the one-sync-per-window contract)
        self._window_t0 = None
        self._window_tokens = 0
        self._last_scale_seen = None
        self._traces_mark = total_traces()
        # cost observatory: (batch shape, dtype) -> static flops/bytes
        # per fused step (loaded from an AOT artifact's manifest at
        # warmup, or via costs.measure_dispatch_costs); step()
        # accumulates the window's static flops so sync() can derive
        # train.mfu_est from the wall it already measures
        self._dispatch_costs: dict = {}
        self._peak_flops = None
        self._window_flops = 0.0
        self._window_bytes = 0.0
        # a window containing a compile-MISS step publishes no MFU:
        # its wall is trace+compile, not model execution (the serving
        # engine's per-dispatch MISS exclusion, at window granularity)
        self._window_miss = False
        self._last_mfu = None

    # -- lr resolution -----------------------------------------------------

    def _lr_mode(self):
        """'traced' — schedule evaluated from the device step counter
        inside the compiled step; 'arg' — lr rides in as a traced scalar
        (float lr, so set_lr works; or a host-only scheduler); 'none' —
        wrapper optimizers whose apply_gradients has no lr kwarg keep
        their own stored rate."""
        if not self._lr_kw:
            return 'none'
        from ..optimizer.lr import LRScheduler

        sched = self.optimizer._learning_rate
        if isinstance(sched, LRScheduler):
            return 'traced' if getattr(sched, 'traceable', True) else 'arg'
        return 'arg'

    def _host_lr(self, lr_mode):
        if lr_mode != 'arg':
            return 0.0
        from ..optimizer.lr import LRScheduler

        sched = self.optimizer._learning_rate
        if isinstance(sched, LRScheduler):
            # host-only scheduler (ReduceOnPlateau): its rate is plain
            # host state — no device readback, no retrace (traced arg)
            if hasattr(sched, 'last_lr'):
                return float(sched.last_lr)
            return float(sched.get_lr_at(self._host_step + 1))
        return float(sched)

    # -- AOT artifact hooks (paddle_tpu.aot) -------------------------------

    def _step_statics(self, lr_mode):
        """The static_argnames kwargs of `_fused_train_step`, in ONE
        place so `step()` and `_warm_geometry` can never drift apart
        (a drifted static is a fresh trace — exactly the cold-start
        cost warmup exists to pre-pay)."""
        return dict(opt=self.optimizer, loss_fn=self.loss_fn,
                    loss_mode=self.loss_mode, accum=self.accum_steps,
                    lr_mode=lr_mode, scaler_cfg=self._scaler_cfg,
                    with_preds=(bool(self.metrics)
                                and self.loss_mode == 'fn'))

    def registry_key(self, batch_shape, batch_dtype):
        """The EXACT TRAIN_COMPILE_CACHE key a `step()` over this batch
        shape notes — tuples of primitives only (see
        inference.engine.CompileCache's key contract)."""
        return (model_tag(self.model), self._engine_id,
                tuple(int(s) for s in batch_shape), str(batch_dtype),
                (self.accum_steps, self._lr_mode(), self.loss_mode,
                 self._scaler_cfg))

    def aot_config(self):
        """Compilation-relevant config as a dict of primitives (the
        artifact-compatibility contract; weight VALUES and host-side
        knobs like log_window are deliberately absent, the model's
        param STRUCTURE rides in as `model_struct`)."""
        opt = self.optimizer
        return {
            'engine': 'TrainEngine',
            'model': model_tag(self.model),
            'model_struct': model_struct(self.model),
            'optimizer': (f'{type(opt).__module__}.'
                          f'{type(opt).__qualname__}'
                          if opt is not None else None),
            'loss_fn': _callable_tag(self.loss_fn),
            'loss_mode': self.loss_mode,
            'lr_mode': self._lr_mode() if opt is not None else None,
            'accum_steps': self.accum_steps,
            'scaler_cfg': (list(self._scaler_cfg)
                           if self._scaler_cfg is not None else None),
            # the mesh geometry is compilation-relevant: a dp=8
            # engine's fused step is an 8-shard SPMD program a
            # mesh-less engine can never look up — attaching across
            # mesh shapes must refuse (ArtifactMismatch names this
            # field)
            'mesh': (dict(self.mesh.shape)
                     if self.mesh is not None else None),
        }

    def _aot_jitted_fns(self):
        """The module-level jitted steps this engine's geometries
        dispatch — what `aot.build` cache-evicts (per FUNCTION, not
        process-wide) to force real persisting compiles."""
        return (_fused_train_step,)

    def _warm_geometry(self, g, draft=None):
        """Drive ONE train-step geometry through `_fused_train_step`
        with dummy zero batches and DEEP-COPIED params / optimizer /
        scaler trees: the copies are what gets donated, so the engine's
        live state is untouched by the warmup step (the optimizer
        result on garbage data is discarded). Statics come from
        `_step_statics`, identical to a real `step()`."""
        if g.kind != 'train_step':
            raise ValueError(
                f'unknown train geometry kind {g.kind!r} (was this '
                f'GeometrySet enumerated for a different engine?)')
        if self.optimizer is None:
            raise RuntimeError('cannot warm a train step without an '
                               'optimizer (eval-only engine)')
        p = g.params

        def zeros(shapes, dtypes):
            return tuple(jnp.zeros(tuple(s), d)
                         for s, d in zip(shapes, dtypes))

        inputs = zeros(p['input_shapes'], p['input_dtypes'])
        labels = zeros(p.get('label_shapes', ()), p.get('label_dtypes', ()))

        def copy_tree(tree):
            # donated leaves must be REAL copies (an aliasing view would
            # hand the live buffer to XLA for in-place reuse); non-array
            # leaves ride through untouched so their avals — including
            # python-scalar weak types — match the real step exactly
            return jax.tree.map(
                lambda x: x.copy() if isinstance(x, jax.Array) else x,
                tree)

        lr_mode = self._lr_mode()
        if inputs:
            TRAIN_COMPILE_CACHE.note(self.registry_key(
                inputs[0].shape, inputs[0].dtype))
        scaler_copy = (copy_tree(self.scaler_state)
                       if self.scaler_state is not None else None)
        _fused_train_step(
            copy_tree(self.model), copy_tree(self.opt_state), scaler_copy,
            inputs, labels, self._host_lr(lr_mode),
            **self._step_statics(lr_mode))

    def warmup(self, artifact=None, geometries=None, draft=None):
        """Pre-populate the fused-train-step jit cache (and the
        TRAIN_COMPILE_CACHE registry) before the first real batch —
        with an `aot.EngineArtifact`, compiles are persistent-cache
        disk reads. Params are NOT touched (the dummy step runs on
        copies). Returns a report dict; see docs/aot_warmup.md."""
        from ..aot.artifact import warm_attach

        return warm_attach(self, artifact=artifact, geometries=geometries,
                           draft=draft)

    def _export_specs(self, g, draft=None):
        """(suffix, jitted_fn, args) for `aot.build(...,
        export_stablehlo=True)` — the fused train step over
        ShapeDtypeStruct batch avals (export only traces; nothing is
        donated or stepped). The model is closed over (the jit.save
        idiom: a Layer in the calling convention would refuse to
        serialize) and the updated params return FLATTENED, so the
        exported module's pytrees carry only arrays, dicts, and
        tuples."""
        if g.kind != 'train_step':
            raise NotImplementedError(
                f'no StableHLO export for geometry kind {g.kind!r}')
        p = g.params

        def sds(shapes, dtypes):
            return tuple(jax.ShapeDtypeStruct(tuple(s), d)
                         for s, d in zip(shapes, dtypes))

        inputs = sds(p['input_shapes'], p['input_dtypes'])
        labels = sds(p.get('label_shapes', ()), p.get('label_dtypes', ()))
        lr_mode = self._lr_mode()
        statics = self._step_statics(lr_mode)
        base = getattr(_fused_train_step, '__wrapped__',
                       _fused_train_step)
        model = self.model

        def step_flat(opt_state, scaler_state, ins, labs, host_lr):
            new_model, new_state, new_scaler, loss, _ = base(
                model, opt_state, scaler_state, ins, labs, host_lr,
                **statics)
            return (tuple(jax.tree.leaves(new_model)), new_state,
                    new_scaler, loss)

        # tracelint: disable=TL001 - one-shot export wrapper, not a hot
        # path
        yield ('', jax.jit(step_flat),
               (self.opt_state, self.scaler_state, inputs, labels,
                self._host_lr(lr_mode)))

    def _cost_specs(self, g, draft=None):
        """(jitted_fn, args, static_kwargs) for
        `observability.costs.geometry_cost`: the module-level fused
        train step over ShapeDtypeStruct batch avals with the live
        model/opt-state riding as arguments — the exact served HLO."""
        if g.kind != 'train_step':
            raise NotImplementedError(
                f'no cost specs for geometry kind {g.kind!r}')
        if self.optimizer is None:
            raise NotImplementedError(
                'eval-only engine: no train step to cost')
        p = g.params

        def sds(shapes, dtypes):
            return tuple(jax.ShapeDtypeStruct(tuple(s), d)
                         for s, d in zip(shapes, dtypes))

        inputs = sds(p['input_shapes'], p['input_dtypes'])
        labels = sds(p.get('label_shapes', ()), p.get('label_dtypes', ()))
        lr_mode = self._lr_mode()
        yield (_fused_train_step,
               (self.model, self.opt_state, self.scaler_state, inputs,
                labels, self._host_lr(lr_mode)),
               self._step_statics(lr_mode))

    def _cost_key(self, shape, dtype):
        return (tuple(int(s) for s in shape), str(dtype))

    def _note_geometry_cost(self, g, cost):
        """Bind one train-step geometry's static flops/bytes (an aot
        manifest `cost` entry, or costs.geometry_cost output) to its
        batch-shape key; `step()` then accumulates window flops and
        `sync()` turns them into `train.mfu_est` — host arithmetic on
        the wall the window sync already measures."""
        if (g.kind != 'train_step' or not isinstance(cost, dict)
                or not cost.get('flops')):
            return
        p = g.params
        self._dispatch_costs[self._cost_key(
            p['input_shapes'][0], p['input_dtypes'][0])] = cost
        if self._peak_flops is None:
            from ..observability import costs as _costs

            self._peak_flops = _costs.device_peak_flops()

    # -- the hot path ------------------------------------------------------

    def step(self, inputs, labels=()):
        """Run one fused train step. Returns the window logs dict when
        this step closes a log window (one device_get), else None."""
        if self.optimizer is None:
            raise RuntimeError('TrainEngine built without an optimizer '
                               'is eval-only; pass one to train')
        if self.loss_mode == 'none':
            # loud failure beats silently "training" on a zero loss
            # while weight decay corrupts the params step by step
            raise RuntimeError(
                'TrainEngine has no loss to train on: pass loss_fn '
                '(hapi prepare(optimizer, loss=...)) or use '
                'loss_fn=None with a model that defines .loss()')
        inputs = tuple(jnp.asarray(x) for x in _to_tuple(inputs))
        labels = tuple(jnp.asarray(x) for x in _to_tuple(labels))
        if self.accum_steps > 1:
            for a in inputs + labels:
                if a.shape[0] % self.accum_steps:
                    raise ValueError(
                        f'global batch {a.shape[0]} not divisible by '
                        f'accum_steps={self.accum_steps}')
        if self._window_t0 is None:        # first step of a new window
            self._window_t0 = time.perf_counter()
        if inputs and hasattr(inputs[0], 'size'):
            self._window_tokens += int(inputs[0].size)
        lr_mode = self._lr_mode()
        if inputs:
            if not TRAIN_COMPILE_CACHE.note(self.registry_key(
                    inputs[0].shape, inputs[0].dtype)):
                self._window_miss = True
            if self._dispatch_costs:
                c = self._dispatch_costs.get(self._cost_key(
                    inputs[0].shape, inputs[0].dtype))
                if c is not None:
                    self._window_flops += c.get('flops') or 0.0
                    self._window_bytes += c.get('bytes_accessed') or 0.0
        (self.model, self.opt_state, self.scaler_state, loss,
         preds) = _fused_train_step(
            self.model, self.opt_state, self.scaler_state, inputs, labels,
            self._host_lr(lr_mode), **self._step_statics(lr_mode))
        self._host_step += 1
        # without metrics only the loss scalar is worth fetching: don't
        # retain (or D2H-transfer) whole pred/label tensors per window
        if self.metrics:
            self._pending.append((loss, preds, labels))
        else:
            self._pending.append((loss, (), ()))
        if len(self._pending) >= self.log_window:
            return self.sync()
        return None

    def sync(self):
        """Close the window: ONE batched device_get for every step since
        the last sync, feed the host metrics, return the logs. Mirrors
        the decode engine's one-sync-per-window contract.

        The telemetry registry is fed HERE and only here (step time,
        tokens/s, loss, loss scale, retrace count) — the current AMP
        scale rides inside the same device_get, so instrumentation adds
        zero extra syncs."""
        if not self._pending:
            return self._last_vals and dict(self._last_vals)
        pending, self._pending = self._pending, []
        # the scaler state is donated to the NEXT step, so fetch the
        # LIVE scale now, folded into the window's one host transfer
        # (holding per-step scale refs would read donated buffers)
        scale_dev = (self.scaler_state['scale']
                     if self.scaler_state is not None else None)
        with _obs_trace.span('train.sync', cat='train',
                             window=len(pending)):
            window, scale = jax.device_get((pending, scale_dev))
        for loss, preds, labels in window:
            self._feed_metrics(preds, labels)
        self._last_loss = float(window[-1][0])
        logs = {'loss': self._last_loss,
                'loss_mean': float(np.mean([w[0] for w in window])),
                'window': len(window)}
        for m in self.metrics:
            names, accs = m.name(), m.accumulate()
            if isinstance(names, list):
                logs.update(dict(zip(names, accs)))
            else:
                logs[names] = accs
        self._last_vals = logs
        self._record_window(len(window), scale)
        return dict(logs)

    def _record_window(self, n_steps, scale):
        """Roll one closed window into the process-global registry
        (host arithmetic on data the sync already fetched)."""
        if not _obs.enabled():
            self._window_t0 = None
            self._window_tokens = 0
            self._window_flops = 0.0
            self._window_bytes = 0.0
            self._window_miss = False
            return
        now = time.perf_counter()
        if self._window_t0 is not None and n_steps:
            wall = now - self._window_t0
            if wall > 0:
                _obs.set_gauge('train.tokens_per_s',
                               self._window_tokens / wall)
                if self._window_flops and not self._window_miss:
                    # live MFU / roofline: the window's accumulated
                    # static step flops (the AOT manifest's cost
                    # stamps) over the wall this sync already measures
                    # — zero extra syncs, zero retraces. A window that
                    # paid a compile publishes nothing (its wall is
                    # not model execution — the MISS-exclusion rule)
                    fps = self._window_flops / wall
                    _obs.set_gauge('train.model_flops_per_s', fps)
                    mfu = (fps / self._peak_flops
                           if self._peak_flops else None)
                    if mfu is not None:
                        _obs.set_gauge('train.mfu_est', mfu)
                    if self._window_bytes:
                        _obs.set_gauge(
                            'train.roofline_intensity',
                            self._window_flops / self._window_bytes)
                    self._last_mfu = {
                        'flops': self._window_flops,
                        'bytes_accessed': self._window_bytes or None,
                        'window_wall_ms': wall * 1e3,
                        'steps': n_steps, 'flops_per_s': fps,
                        'mfu_est': mfu,
                        'peak_flops': self._peak_flops,
                    }
            # per-step time is known at window granularity only (the
            # steps never synced individually — that is the point)
            _obs.observe('train.step_ms', wall * 1e3 / n_steps,
                         n=n_steps)
        _obs.inc('train.steps', n_steps)
        _obs.inc('train.tokens', self._window_tokens)
        _obs.set_gauge('train.loss', self._last_loss)
        _obs.set_gauge('train.accum_steps', self.accum_steps)
        traces = total_traces()
        # clamp: a reset_trace_counts() between windows would otherwise
        # make the delta negative and Counter.inc raise mid-sync
        _obs.inc('train.traces', max(0, traces - self._traces_mark))
        self._traces_mark = traces
        if scale is not None:
            s = float(scale)
            _obs.set_gauge('train.loss_scale', s)
            # a scale DROP between windows means the in-trace skip path
            # fired at least once inside the window (window-granular by
            # design: per-step skip visibility would cost a sync)
            if (self._last_scale_seen is not None
                    and s < self._last_scale_seen):
                _obs.inc('train.scale_backoffs')
            self._last_scale_seen = s
        # the windowed timeseries commits at THIS existing sync point
        # (the training mirror of the serving per-window commit): the
        # process-default ring derives train.tok_s and windowed
        # train.step_ms percentiles with zero new syncs
        _obs_ts.TIMESERIES.maybe_commit(now)
        self._window_t0 = None
        self._window_tokens = 0
        self._window_flops = 0.0
        self._window_bytes = 0.0
        self._window_miss = False

    def _feed_metrics(self, preds, labels):
        if preds is None or (isinstance(preds, tuple) and not preds):
            return
        for m in self.metrics:
            args = m.compute(preds, *labels)
            if not isinstance(args, tuple):
                args = (args,)
            m.update(*args)

    # -- eval --------------------------------------------------------------

    def eval_step(self, inputs, labels=()):
        """Buffer one eval batch on device (no host sync); windows flush
        through eval_sync() / automatically every log_window batches."""
        inputs = tuple(jnp.asarray(x) for x in _to_tuple(inputs))
        labels = tuple(jnp.asarray(x) for x in _to_tuple(labels))
        with_preds = bool(self.metrics) and self.loss_mode != 'model'
        loss, preds = _eval_step(self.model, inputs, labels,
                                 loss_fn=self.loss_fn,
                                 loss_mode=self.loss_mode,
                                 with_preds=with_preds)
        if self.metrics:
            self._eval_pending.append((loss, preds, labels))
        else:
            self._eval_pending.append((loss, (), ()))
        if len(self._eval_pending) >= self.log_window:
            return self.eval_sync()
        return None

    def eval_sync(self):
        """One device_get for the buffered eval window; returns the list
        of host losses (metrics are fed as a side effect)."""
        if not self._eval_pending:
            return []
        pending, self._eval_pending = self._eval_pending, []
        window = jax.device_get(pending)
        for loss, preds, labels in window:
            self._feed_metrics(preds, labels)
        return [float(w[0]) for w in window]

    # -- input side --------------------------------------------------------

    def prefetch(self, iterator, size=2):
        """Wrap a host batch iterator with sharded device prefetch:
        `size` global batches stay in flight to HBM (H2D overlaps
        compute), each sharded over the mesh's data axes when the
        engine has one (dp/fsdp global arrays)."""
        from ..io.dataloader import prefetch_to_device

        sharding = None
        if self.mesh is not None:
            from ..distributed.sharding import data_sharding

            sharding = data_sharding(self.mesh)
        return prefetch_to_device(iterator, size=size, sharding=sharding)

    # -- bookkeeping -------------------------------------------------------

    def loss_scale(self):
        """Current AMP loss scale (host float; one off-hot-path sync)."""
        if self.scaler_state is None:
            return 1.0
        return float(jax.device_get(self.scaler_state['scale']))

    def stats(self):
        """{'trace_counts', 'total_traces', 'cache_keys', 'hits',
        'misses'} — steady-state training must show total_traces frozen
        across steps (bench.py asserts exactly that)."""
        return {
            'trace_counts': trace_counts(),
            'total_traces': total_traces(),
            'cache_keys': len(TRAIN_COMPILE_CACHE),
            'hits': TRAIN_COMPILE_CACHE.hits,
            'misses': TRAIN_COMPILE_CACHE.misses,
            # host-truth MFU record of the last closed window (static
            # window flops, wall, mfu_est) — what tests check the
            # train.mfu_est gauge against
            'mfu': self._last_mfu,
        }


__all__ = [
    'TrainEngine', 'TRAIN_COMPILE_CACHE', 'trace_counts', 'total_traces',
    'reset_trace_counts',
]
