"""FFT namespace (ref: python/paddle/fft.py) — jnp.fft lowered to XLA."""
from __future__ import annotations

import jax.numpy as jnp

_f = jnp.fft

fft = _f.fft
ifft = _f.ifft
fft2 = _f.fft2
ifft2 = _f.ifft2
fftn = _f.fftn
ifftn = _f.ifftn
rfft = _f.rfft
irfft = _f.irfft
rfft2 = _f.rfft2
irfft2 = _f.irfft2
rfftn = _f.rfftn
irfftn = _f.irfftn
hfft = _f.hfft
ihfft = _f.ihfft
fftfreq = _f.fftfreq
rfftfreq = _f.rfftfreq
fftshift = _f.fftshift
ifftshift = _f.ifftshift

__all__ = [
    'fft', 'ifft', 'fft2', 'ifft2', 'fftn', 'ifftn', 'rfft', 'irfft',
    'rfft2', 'irfft2', 'rfftn', 'irfftn', 'hfft', 'ihfft', 'fftfreq',
    'rfftfreq', 'fftshift', 'ifftshift',
]


def hfftn(x, s=None, axes=None, norm='backward', name=None):
    """N-D FFT of a signal with Hermitian symmetry along the last
    transform axis -> real output (ref: paddle.fft.hfftn; jnp has no
    hfftn, but axis transforms commute, so this is fftn over the leading
    axes composed with hfft over the last)."""
    x = jnp.asarray(x)
    if axes is None:
        axes = tuple(range(x.ndim))
    axes = tuple(a % x.ndim for a in axes)
    s_lead = tuple(s[:-1]) if s is not None else None
    n_last = s[-1] if s is not None else None
    out = _f.fftn(x, s=s_lead, axes=axes[:-1], norm=norm) if len(axes) > 1 else x
    return _f.hfft(out, n=n_last, axis=axes[-1], norm=norm)


def ihfftn(x, s=None, axes=None, norm='backward', name=None):
    """Inverse of hfftn: real input -> Hermitian half-spectrum
    (ref: paddle.fft.ihfftn)."""
    x = jnp.asarray(x)
    if axes is None:
        axes = tuple(range(x.ndim))
    axes = tuple(a % x.ndim for a in axes)
    n_last = s[-1] if s is not None else None
    out = _f.ihfft(x, n=n_last, axis=axes[-1], norm=norm)
    if len(axes) > 1:
        s_lead = tuple(s[:-1]) if s is not None else None
        out = _f.ifftn(out, s=s_lead, axes=axes[:-1], norm=norm)
    return out


def hfft2(x, s=None, axes=(-2, -1), norm='backward', name=None):
    """ref: paddle.fft.hfft2."""
    return hfftn(x, s=s, axes=axes, norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm='backward', name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm)


__all__ += ['hfft2', 'ihfft2', 'hfftn', 'ihfftn']
