"""FFT namespace (ref: python/paddle/fft.py) — jnp.fft lowered to XLA."""
from __future__ import annotations

import jax.numpy as jnp

_f = jnp.fft

fft = _f.fft
ifft = _f.ifft
fft2 = _f.fft2
ifft2 = _f.ifft2
fftn = _f.fftn
ifftn = _f.ifftn
rfft = _f.rfft
irfft = _f.irfft
rfft2 = _f.rfft2
irfft2 = _f.irfft2
rfftn = _f.rfftn
irfftn = _f.irfftn
hfft = _f.hfft
ihfft = _f.ihfft
fftfreq = _f.fftfreq
rfftfreq = _f.rfftfreq
fftshift = _f.fftshift
ifftshift = _f.ifftshift

__all__ = [
    'fft', 'ifft', 'fft2', 'ifft2', 'fftn', 'ifftn', 'rfft', 'irfft',
    'rfft2', 'irfft2', 'rfftn', 'irfftn', 'hfft', 'ihfft', 'fftfreq',
    'rfftfreq', 'fftshift', 'ifftshift',
]
