"""Quantization (ref: python/paddle/quantization + paddle.nn.quant).

PTQ int8 weight-only: `quantize_model` walks a model's Linear layers,
replacing fp weights with (int8, scale) pairs served by the pallas
quantized matmul. Absmax observer; per-output-channel scales.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..nn.layer.base import Layer, Parameter
from ..nn.quant import QuantizedWeight  # noqa: F401
from ..ops.pallas.quant_matmul import (  # noqa: F401
    quant_matmul,
    quantize_weight,
    weight_only_linear,
)


class QuantizedLinear(Layer):
    """Weight-only int8/int4 Linear (ref: paddle.nn.quant
    .weight_only_linear). ``bits=4`` packs two codes per byte — half the
    weight HBM traffic of int8."""

    def __init__(self, linear=None, weight_quantize_type='abs_max', bits=8):
        super().__init__()
        if bits not in (4, 8):
            raise ValueError(f'bits must be 4 or 8, got {bits}')
        self.bits = bits
        if linear is not None:
            from ..nn.quant import weight_quantize

            wq, scale = weight_quantize(
                linear.weight,
                algo='weight_only_int4' if bits == 4 else 'weight_only_int8')
            self.weight_q = Parameter(wq, trainable=False)
            self.scale = Parameter(scale, trainable=False)
            self.bias = linear.bias
            self.in_features = linear.in_features
            self.out_features = linear.out_features

    def forward(self, x):
        return weight_only_linear(
            x, self.weight_q, self.scale, self.bias,
            weight_dtype='int4' if self.bits == 4 else 'int8')


def quantize_model(model, quantizable=('Linear',), inplace=False, bits=8):
    """PTQ pass: swap matching sublayers for QuantizedLinear (``bits``:
    8 or 4 — int4 packs two codes per byte).

    Returns the (new) model; original untouched unless inplace.
    """
    from ..nn.layer.common import Linear

    if 'Linear' not in quantizable:
        return model
    return _replace_layers(model, lambda c: isinstance(c, Linear),
                           lambda c: QuantizedLinear(c, bits=bits), inplace)


def quantize_matmul_weights(model, bits=8, min_features=64, exclude=()):
    """Weight-only PTQ for raw-`x @ w` models (ref capability: the
    serving-side weight_only pass of paddle.quantization): every
    trainable 2-D floating param with min(shape) >= min_features becomes
    a `QuantizedWeight` served by the pallas int8/int4 kernels.

    This covers models that hold projections as bare Parameters (llama,
    gpt) — `quantize_model` handles nn.Linear-built ones. Exclusion is
    STRUCTURAL, not name-based: `nn.Embedding` subtrees are never
    touched (gathered, not matmul'd), and a layer class opts out by
    declaring ``no_quantize = True`` (whole subtree — e.g. MoE router
    gates, where int8 noise flips top-k expert selection) or a tuple of
    its param names (lookup tables held as raw Parameters, e.g. a
    model's ``embed_tokens``). `exclude` adds user path-substring
    excludes on top. Returns a new model; the original is untouched.

    3-D batched MoE expert weights (E, in, out) quantize too at
    bits=8 (QuantizedExpertWeight, per-(expert, out-col) scales; int4
    expert packing is not implemented so bits=4 leaves experts fp).

    Known limitations (weight bytes that do NOT shrink):
      - tied LM heads served as ``embed_tokens.T`` ride the (excluded)
        embedding table, so the head matmul stays full precision;
      - the ragged (dropless) MoE path — which KV-cached MoE DECODE
        always uses — dequantizes experts before lax.ragged_dot, so
        expert int8 is a checkpoint/footprint win there, not a
        guaranteed decode-bandwidth win; the dense/GShard einsum
        (train/prefill) streams int8.
    """
    import jax

    from ..nn.layer.common import Embedding
    from ..nn.quant import QuantizedWeight

    new = jax.tree_util.tree_map(lambda x: x, model)

    def walk(sub, path):
        nq = getattr(sub, 'no_quantize', ())
        if nq is True or isinstance(sub, Embedding):
            return
        for name in sorted(sub.__dict__):
            v = sub.__dict__[name]
            full = f'{path}.{name}' if path else name
            if isinstance(v, Layer):
                walk(v, full)
                continue
            meta = sub._param_meta.get(name)
            if meta is None or meta.kind != 'param' or not meta.trainable:
                continue
            if name in nq or any(e in full for e in exclude):
                continue
            nd = getattr(v, 'ndim', 0)
            if nd not in (2, 3) or min(v.shape[-2:]) < min_features:
                continue
            if not (jnp.issubdtype(v.dtype, jnp.floating)
                    or v.dtype == jnp.bfloat16):
                continue
            if nd == 3:
                # batched MoE expert weights (E, K, N): int8 with
                # per-(expert, out-col) scales (int4 packing is 2-D only)
                if bits != 8:
                    continue
                from ..nn.quant import QuantizedExpertWeight

                qw = QuantizedExpertWeight.quantize(v, bits)
            else:
                qw = QuantizedWeight.quantize(v, bits)
            sub.__dict__[name] = qw
            # keep the sharding spec when the codes preserve the dense
            # shape (int8): a quantize-then-parallelize flow must not
            # silently replicate ep/tp-sharded weights. int4 packs the
            # leading dim, so its spec is dropped (today's behavior).
            keep = (meta.spec
                    if tuple(qw.codes.shape) == tuple(v.shape) else None)
            sub.set_param_meta(name, trainable=False, spec=keep)

    walk(new, '')
    return new


def _replace_layers(model, match, build, inplace=False):
    """Shared PTQ/QAT traversal: structural-copy (unless inplace), then
    recursively swap every child where ``match(child)`` for
    ``build(child)``."""
    import jax

    if not inplace:
        leaves, treedef = jax.tree.flatten(model)
        model = jax.tree.unflatten(treedef, leaves)   # structural copy

    def walk(layer):
        for name, child in list(layer.__dict__.items()):
            if match(child):
                layer.__dict__[name] = build(child)
            elif isinstance(child, Layer):
                walk(child)
        return layer

    return walk(model)


class _ObservedLinear(Layer):
    """Calibration wrapper: fp32 passthrough that feeds the activation
    observer (ref quantization/ptq.py inserts observer hooks)."""

    def __init__(self, inner, act_observer):
        super().__init__()
        self.inner = inner
        self._obs = act_observer

    def forward(self, x):
        self._obs.observe(x)
        return self.inner(x)


class PTQ:
    """ref: paddle.quantization.PTQ — the full post-training flow:

        ptq = PTQ(QuantConfig())
        observed = ptq.quantize(model)       # insert observers
        for batch in calib_loader:           # calibration (eager)
            observed(batch)
        infer_model = ptq.convert(observed)  # int8 weight-only Linears

    `quantize` leaves the numerics untouched (observers are identity);
    `convert` swaps each observed Linear for a QuantizedLinear, keeping
    the observed activation scale on the layer for introspection /
    static-quant consumers.
    """

    def __init__(self, config=None, weight_bits=8):
        self.config = config or QuantConfig()
        self.weight_bits = weight_bits

    def quantize(self, model, inplace=False):
        from ..nn.layer.common import Linear

        def build(child):
            a_cls, _ = self.config._for_layer(child)
            return _ObservedLinear(child, (a_cls or BaseObserver)())

        return _replace_layers(model, lambda c: isinstance(c, Linear),
                               build, inplace)

    def convert(self, model, inplace=False):
        def build(child):
            q = QuantizedLinear(child.inner, bits=self.weight_bits)
            object.__setattr__(q, 'act_scale', child._obs.scales())
            return q

        return _replace_layers(model,
                               lambda c: isinstance(c, _ObservedLinear),
                               build, inplace)


class BaseObserver:
    """ref: paddle.quantization.BaseObserver — watches activations /
    weights to derive quant params (scale, zero point). State only
    updates from CONCRETE values: under jit tracing the batch statistic
    is a tracer that must not be stored (it would leak out of the trace)
    — the per-call scale below is a pure function of x, so correctness
    inside jit never depends on this running state."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self._absmax = None

    def observe(self, x):
        import jax
        import jax.numpy as jnp

        m = jnp.max(jnp.abs(x))
        if not isinstance(m, jax.core.Tracer):
            self._absmax = m if self._absmax is None else jnp.maximum(
                self._absmax, m)
        return x

    def scales(self):
        if self._absmax is None:
            return None
        return self._absmax / (2 ** (self.quant_bits - 1) - 1)


class BaseQuanter(BaseObserver):
    """ref: paddle.quantization.BaseQuanter — fake-quantizes in forward
    (straight-through estimator). The quant scale is computed from the
    CURRENT tensor (pure, jit-safe); eager calls additionally fold the
    statistic into the running observer state for `convert`."""

    def __call__(self, x):
        import jax
        import jax.numpy as jnp

        self.observe(x)
        absmax = jax.lax.stop_gradient(jnp.max(jnp.abs(x)))
        scale = absmax / (2 ** (self.quant_bits - 1) - 1)
        scale = jnp.where(scale == 0, 1.0, scale)
        q = jnp.round(x / scale)
        q = jnp.clip(q, -(2 ** (self.quant_bits - 1)),
                     2 ** (self.quant_bits - 1) - 1)
        # straight-through: quantized value, identity gradient
        return x + jax.lax.stop_gradient(q * scale - x)


def quanter(cls):
    """ref: paddle.quantization.quanter — class decorator registering a
    custom quanter type."""
    _QUANTER_REGISTRY[cls.__name__] = cls
    return cls


_QUANTER_REGISTRY = {}


class QuantConfig:
    """ref: paddle.quantization.QuantConfig — which layers get which
    activation/weight quanters."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._layer_configs = []
        self._type_configs = []

    def add_layer_config(self, layer, activation=None, weight=None):
        self._layer_configs.append((layer, activation, weight))

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        for t in types:
            self._type_configs.append((t, activation, weight))

    def _for_layer(self, layer):
        for lyr, a, w in self._layer_configs:
            if lyr is layer:
                return a, w
        for t, a, w in self._type_configs:
            if isinstance(layer, t):
                return a, w
        return self.activation, self.weight


class QAT:
    """Quantization-aware training (ref: paddle.quantization.QAT):
    wraps Linear layers so forward fake-quantizes weights and
    activations with straight-through gradients — the model learns
    around the rounding it will see at int8 inference, then `convert`
    hands the observed scales to the PTQ weight-only path."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=False):
        from ..nn.layer.common import Linear

        def build(child):
            a_cls, w_cls = self.config._for_layer(child)
            return _QATLinear(child, (a_cls or BaseQuanter)(),
                              (w_cls or BaseQuanter)())

        return _replace_layers(model, lambda c: isinstance(c, Linear),
                               build, inplace)

    def convert(self, model, inplace=False):
        """Swap QAT wrappers for the int8 weight-only inference path."""
        return _replace_layers(model,
                               lambda c: isinstance(c, _QATLinear),
                               lambda c: quantize_layer(c.inner), inplace)


def quantize_layer(linear):
    """One Linear -> QuantizedLinear (int8 weight-only)."""
    return QuantizedLinear(linear)


class _QATLinear(Layer):
    def __init__(self, inner, act_quanter, weight_quanter):
        super().__init__()
        self.inner = inner
        self._act_q = act_quanter
        self._weight_q = weight_quanter

    def forward(self, x):
        from ..nn import functional as F

        x = self._act_q(x)
        w = self._weight_q(self.inner.weight)
        return F.linear(x, w, self.inner.bias)
