"""Quantization (ref: python/paddle/quantization + paddle.nn.quant).

PTQ int8 weight-only: `quantize_model` walks a model's Linear layers,
replacing fp weights with (int8, scale) pairs served by the pallas
quantized matmul. Absmax observer; per-output-channel scales.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..nn.layer.base import Layer, Parameter
from ..ops.pallas.quant_matmul import (  # noqa: F401
    quant_matmul,
    quantize_weight,
    weight_only_linear,
)


class QuantizedLinear(Layer):
    """Weight-only int8 Linear (ref: paddle.nn.quant.weight_only_linear)."""

    def __init__(self, linear=None, weight_quantize_type='abs_max'):
        super().__init__()
        if linear is not None:
            wq, scale = quantize_weight(linear.weight)
            self.weight_q = Parameter(wq, trainable=False)
            self.scale = Parameter(scale, trainable=False)
            self.bias = linear.bias
            self.in_features = linear.in_features
            self.out_features = linear.out_features

    def forward(self, x):
        return weight_only_linear(x, self.weight_q, self.scale, self.bias)


def quantize_model(model, quantizable=('Linear',), inplace=False):
    """PTQ pass: swap matching sublayers for QuantizedLinear.

    Returns the (new) model; original untouched unless inplace.
    """
    from ..nn.layer.common import Linear

    if not inplace:
        import jax

        leaves, treedef = jax.tree.flatten(model)
        model = jax.tree.unflatten(treedef, leaves)   # structural copy
    for _, layer in model.named_sublayers(include_self=True):
        for name, child in list(layer._children()):
            if isinstance(child, Linear) and 'Linear' in quantizable:
                object.__setattr__(layer, name, QuantizedLinear(child))
    return model


class PTQ:
    """ref: paddle.quantization.PTQ facade."""

    def __init__(self, config=None):
        self.config = config

    def quantize(self, model, inplace=False):
        return quantize_model(model, inplace=inplace)
