"""paddle_tpu.onnx (ref: python/paddle/onnx/__init__.py — `export`).

The reference exports through paddle2onnx to the ONNX graph IR. The
TPU-native interchange format is StableHLO (via `jax.export`), which is
what every XLA consumer loads; `export` therefore produces a
`.mlir`+weights pair through `jit.save` and says so, rather than
pretending to emit ONNX protobufs.
"""
from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """ref: paddle.onnx.export — here: StableHLO export.

    Writes `path + '.mlir'` (serialized StableHLO) and
    `path + '.pdiparams'` (weights), the same artifacts `jit.save`
    produces and `jit.load` restores.
    """
    from ..jit import save as jit_save

    jit_save(layer, path, input_spec=input_spec, **configs)
    return path + '.mlir'
