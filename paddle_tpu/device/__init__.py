"""Device management (ref: python/paddle/device/__init__.py).

Paddle's CUDAPlace/CPUPlace become jax devices; `TPUPlace` is the
first-class accelerator. XLA owns streams/allocators, so the Paddle
stream & memory APIs map to introspection + donation hints.
"""
from __future__ import annotations

import jax


class _Place:
    def __init__(self, platform, device_id=0):
        self._platform = platform
        self._id = device_id

    def get_device_id(self):
        return self._id

    def __repr__(self):
        return f"Place({self._platform}:{self._id})"

    def __eq__(self, other):
        return (
            isinstance(other, _Place)
            and self._platform == other._platform
            and self._id == other._id
        )

    def __hash__(self):
        return hash((self._platform, self._id))

    def jax_device(self):
        devs = [d for d in jax.devices() if d.platform == self._platform] or (
            jax.devices('cpu')
        )
        return devs[min(self._id, len(devs) - 1)]


class TPUPlace(_Place):
    def __init__(self, device_id=0):
        platform = jax.default_backend()
        if platform == 'cpu':
            # virtual-mesh testing: TPUPlace degrades to host devices
            super().__init__('cpu', device_id)
        else:
            super().__init__(platform, device_id)


class CPUPlace(_Place):
    def __init__(self, device_id=0):
        super().__init__('cpu', device_id)


# CUDAPlace alias: lets reference training scripts that name CUDAPlace run
# unchanged on TPU (the BASELINE north-star swap).
CUDAPlace = TPUPlace
XPUPlace = TPUPlace

_current = [None]


def set_device(device):
    """ref: paddle.device.set_device ('tpu', 'cpu', 'tpu:0', ...)."""
    if isinstance(device, _Place):
        _current[0] = device
        return device
    name, _, idx = str(device).partition(':')
    idx = int(idx) if idx else 0
    if name in ('tpu', 'gpu', 'cuda', 'xpu', 'axon'):
        _current[0] = TPUPlace(idx)
    else:
        _current[0] = CPUPlace(idx)
    return _current[0]


def get_device():
    if _current[0] is None:
        _current[0] = TPUPlace(0)
    p = _current[0]
    return f"{p._platform}:{p._id}"


def get_default_place():
    if _current[0] is None:
        _current[0] = TPUPlace(0)
    return _current[0]


def device_count(platform=None):
    return jax.device_count()


def local_device_count():
    return jax.local_device_count()


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_tpu():
    return jax.default_backend() not in ('cpu',)


class cuda:
    """Namespace parity for paddle.device.cuda memory stats."""

    @staticmethod
    def memory_allocated(device=None):
        stats = jax.local_devices()[0].memory_stats() or {}
        return stats.get('bytes_in_use', 0)

    @staticmethod
    def max_memory_allocated(device=None):
        stats = jax.local_devices()[0].memory_stats() or {}
        return stats.get('peak_bytes_in_use', 0)

    @staticmethod
    def empty_cache():
        return None

    @staticmethod
    def synchronize(device=None):
        for d in jax.live_arrays():
            d.block_until_ready()


def synchronize():
    import jax.numpy as jnp

    jnp.zeros(()).block_until_ready()


# ---- feature probes & stream compat (ref: python/paddle/device) -----------
# CUDA/ROCm/IPU/CINN probes answer honestly for a TPU/XLA build; the
# stream API maps onto XLA's implicit async dispatch (one compute stream
# per device, synchronization via block_until_ready).


def get_cudnn_version():
    """ref: paddle.device.get_cudnn_version — None: no cuDNN here."""
    return None


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    """CINN's role (graph compilation) is played by XLA, but the CINN
    binary itself is not present."""
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_distribute():
    """Distributed is always available (XLA collectives are built in)."""
    return True


def is_compiled_with_custom_device(device_type=None):
    return False


IPUPlace = CPUPlace  # accepted for script compat; degrades to host


def get_all_device_type():
    import jax

    return sorted({d.platform for d in jax.devices()} | {'cpu'})


def get_all_custom_device_type():
    return []


def get_available_device():
    import jax

    return [f'{d.platform}:{d.id}' for d in jax.devices()]


def get_available_custom_device():
    return []


class Stream:
    """ref: paddle.device.Stream. XLA runs one ordered async compute
    stream per device; this object names it for API compatibility and
    `synchronize` drains it."""

    def __init__(self, device=None, priority=2):
        self.device = device
        self.priority = priority

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        event.synchronize()

    def wait_stream(self, stream):
        stream.synchronize()

    def record_event(self, event=None):
        event = event or Event()
        event.record(self)
        return event

    def query(self):
        return True


class Event:
    """ref: paddle.device.Event — completion marker on the XLA stream."""

    def __init__(self, device=None, enable_timing=False, blocking=False,
                 interprocess=False):
        self._recorded = False

    def record(self, stream=None):
        self._recorded = True

    def query(self):
        return True

    def synchronize(self):
        synchronize()


_current_stream = [None]


def current_stream(device=None):
    if _current_stream[0] is None:
        _current_stream[0] = Stream(device)
    return _current_stream[0]


def set_stream(stream):
    prev = current_stream()
    _current_stream[0] = stream
    return prev


class stream_guard:
    """ref: paddle.device.stream_guard — context switching the current
    stream (a no-op ordering-wise: XLA keeps program order)."""

    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        self._prev = set_stream(self.stream)
        return self.stream

    def __exit__(self, *exc):
        set_stream(self._prev)
        return False
