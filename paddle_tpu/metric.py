"""Metrics (ref: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np


class Metric:
    """ref: paddle.metric.Metric — accumulating metric base."""

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return type(self).__name__.lower()

    # hapi hook: turn (pred, label) into update() args
    def compute(self, pred, label, *args):
        return pred, label


class Accuracy(Metric):
    """ref: paddle.metric.Accuracy (top-k)."""

    def __init__(self, topk=(1,), name='acc'):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self._name = name
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred = np.asarray(pred)
        label = np.asarray(label)
        maxk = max(self.topk)
        order = np.argsort(-pred, axis=-1)[..., :maxk]
        if label.ndim == pred.ndim:       # one-hot / soft labels
            label = label.argmax(-1)
        correct = order == label[..., None]
        return correct

    def update(self, correct):
        correct = np.asarray(correct)
        n = correct[..., 0].size
        for i, k in enumerate(self.topk):
            self.total[i] += correct[..., :k].any(-1).sum()
            self.count[i] += n
        return self.total / np.maximum(self.count, 1)

    def accumulate(self):
        acc = self.total / np.maximum(self.count, 1)
        return acc[0] if len(self.topk) == 1 else list(acc)

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f'{self._name}_top{k}' for k in self.topk]


class Precision(Metric):
    """Binary precision (ref: paddle.metric.Precision)."""

    def __init__(self, name='precision'):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (np.asarray(preds).reshape(-1) > 0.5).astype(int)
        labels = np.asarray(labels).reshape(-1).astype(int)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall (ref: paddle.metric.Recall)."""

    def __init__(self, name='recall'):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (np.asarray(preds).reshape(-1) > 0.5).astype(int)
        labels = np.asarray(labels).reshape(-1).astype(int)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via threshold buckets (ref: paddle.metric.Auc)."""

    def __init__(self, num_thresholds=4095, name='auc'):
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        self._pos = np.zeros(self.num_thresholds + 1)
        self._neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        idx = np.clip((preds * self.num_thresholds).astype(int), 0,
                      self.num_thresholds)
        np.add.at(self._pos, idx, labels == 1)
        np.add.at(self._neg, idx, labels == 0)

    def accumulate(self):
        tot_pos = self._pos.sum()
        tot_neg = self._neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # walk thresholds high→low accumulating TPR/FPR trapezoids
        tp = np.cumsum(self._pos[::-1])
        fp = np.cumsum(self._neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None):
    """Functional top-k accuracy (ref: python/paddle/metric/metrics.py::
    accuracy). input: (N, C) scores; label: (N,) or (N, 1) int."""
    import jax.numpy as jnp

    input = jnp.asarray(input)
    label = jnp.asarray(label).reshape(-1)
    topk = jnp.argsort(-input, axis=-1)[:, :k]
    hit = jnp.any(topk == label[:, None], axis=-1)
    return jnp.mean(hit.astype(jnp.float32))
