"""Weight initializers (ref: python/paddle/nn/initializer/*).

Each initializer is ``__call__(shape, dtype) -> jax.Array``, drawing from
the process-global PRNG stream (framework.random) — init happens in
eager code, so the functional-key plumbing stays out of user sight.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as random_mod


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels, NHWC-native layout (kh, kw, cin, cout)
    receptive = int(np.prod(shape[:-2]))
    return shape[-2] * receptive, shape[-1] * receptive


class Initializer:
    def __call__(self, shape, dtype):  # pragma: no cover - abstract
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        k = random_mod.split_key()
        return (
            jax.random.normal(k, shape, dtype=jnp.float32) * self.std + self.mean
        ).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        k = random_mod.split_key()
        x = jax.random.truncated_normal(k, self.a, self.b, shape, dtype=jnp.float32)
        return (x * self.std + self.mean).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        k = random_mod.split_key()
        return jax.random.uniform(
            k, shape, dtype=jnp.float32, minval=self.low, maxval=self.high
        ).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity='relu'):
        self.fan_in, self.negative_slope = fan_in, negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2))
        return Normal(0.0, gain / math.sqrt(fi))(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity='relu'):
        self.fan_in, self.negative_slope = fan_in, negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2))
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, shape, dtype):
        assert tuple(self.value.shape) == tuple(shape), (self.value.shape, shape)
        return jnp.asarray(self.value, dtype=dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        k = random_mod.split_key()
        return jax.nn.initializers.orthogonal(self.gain)(k, shape, jnp.float32).astype(
            dtype
        )


# Paddle-compatible aliases
TruncatedNormalInitializer = TruncatedNormal
NormalInitializer = Normal
ConstantInitializer = Constant


def calculate_gain(nonlinearity, param=None):
    """ref: paddle.nn.initializer.calculate_gain."""
    import math

    gains = {
        'linear': 1.0, 'conv1d': 1.0, 'conv2d': 1.0, 'conv3d': 1.0,
        'conv1d_transpose': 1.0, 'conv2d_transpose': 1.0,
        'conv3d_transpose': 1.0, 'sigmoid': 1.0,
        'tanh': 5.0 / 3.0, 'relu': math.sqrt(2.0),
        'selu': 3.0 / 4.0,
    }
    if nonlinearity == 'leaky_relu':
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity not in gains:
        raise ValueError(f'unsupported nonlinearity: {nonlinearity}')
    return gains[nonlinearity]


class Dirac(Initializer):
    """Identity-preserving conv kernel init (ref: initializer/dirac.py):
    out[i, i % C_in, center...] = 1 within each of `groups` blocks."""

    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype=None):
        import numpy as np

        arr = np.zeros(shape, np.float32)
        c_out, c_in = shape[0], shape[1]
        center = tuple(s // 2 for s in shape[2:])
        per_group = c_out // self.groups
        # only the first min(per_group, c_in) outputs of each group carry
        # an identity tap; the rest stay zero (ref: initializer/dirac.py
        # min_shape clamp — wrapping extra outputs would duplicate inputs)
        taps = min(per_group, c_in)
        for g in range(self.groups):
            for i in range(taps):
                arr[(g * per_group + i, i) + center] = 1.0
        return jnp.asarray(arr, dtype)


class Bilinear(Initializer):
    """Bilinear-upsampling transposed-conv kernel
    (ref: initializer/Bilinear)."""

    def __call__(self, shape, dtype=None):
        import numpy as np

        if len(shape) < 3:
            raise ValueError('Bilinear init expects a conv kernel shape')
        spatial = shape[2:]
        weights = np.ones((1,), np.float32)
        for s in spatial:
            factor = (s + 1) // 2
            if s % 2 == 1:
                center = factor - 1.0
            else:
                center = factor - 0.5
            og = np.arange(s, dtype=np.float32)
            filt = 1.0 - np.abs(og - center) / factor
            weights = np.outer(weights.ravel(), filt)
        weights = weights.reshape(spatial)
        arr = np.zeros(shape, np.float32)
        for i in range(shape[0]):
            for j in range(shape[1]):
                arr[i, j] = weights
        return jnp.asarray(arr, dtype)


_global_initializer = [None]


def set_global_initializer(weight_init, bias_init=None):
    """ref: paddle.nn.initializer.set_global_initializer — default
    initializers used by create_parameter when none is given."""
    _global_initializer[0] = (weight_init, bias_init)


def get_global_initializer():
    return _global_initializer[0]
