"""Convolutions (ref: python/paddle/nn/functional/conv.py).

All convs lower to `lax.conv_general_dilated` → XLA tiles them onto the
MXU. Paddle's default layout is NCHW; TPUs prefer channels-last, so the
functional API accepts both and the Layer classes default to NCHW for
API parity while converting internally only when asked.
Weight layout follows Paddle: (out_ch, in_ch/groups, *kernel).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


def _padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _dn(n, data_format):
    if data_format in ('NCHW', 'NCL', 'NCDHW'):
        lhs = 'NC' + 'DHW'[3 - n :]
        out = lhs
    else:
        lhs = 'N' + 'DHW'[3 - n :] + 'C'
        out = lhs
    rhs = 'OI' + 'DHW'[3 - n :]
    return (lhs, rhs, out)


def _conv(x, weight, bias, stride, padding, dilation, groups, n, data_format):
    dn = lax.conv_dimension_numbers(x.shape, weight.shape, _dn(n, data_format))
    out = lax.conv_general_dilated(
        x,
        weight,
        window_strides=_tuple(stride, n),
        padding=_padding(padding, n),
        rhs_dilation=_tuple(dilation, n),
        dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.bfloat16 else None,
    )
    out = out.astype(x.dtype)
    if bias is not None:
        shape = [1] * out.ndim
        shape[1 if data_format.startswith('NC') else -1] = bias.size
        out = out + bias.reshape(shape)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format='NCL'):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format='NCHW'):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format='NCDHW'):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(
    x, weight, bias, stride, padding, output_padding, dilation, groups, n, data_format
):
    # Paddle stores transpose-conv weight as (in_ch, out_ch/groups, *k)
    dn = lax.conv_dimension_numbers(
        x.shape, (weight.shape[1] * groups, weight.shape[0] // groups) + weight.shape[2:],
        _dn(n, data_format),
    )
    pad = _padding(padding, n)
    if isinstance(pad, str):
        pad_pairs = [(0, 0)] * n if pad == 'VALID' else None
    else:
        pad_pairs = pad
    strides = _tuple(stride, n)
    dil = _tuple(dilation, n)
    k = weight.shape[2:]
    opad = _tuple(output_padding, n)
    if pad_pairs is None:
        trans_pad = 'SAME'
    else:
        trans_pad = []
        for i in range(n):
            eff_k = (k[i] - 1) * dil[i] + 1
            lo = eff_k - 1 - pad_pairs[i][0]
            hi = eff_k - 1 - pad_pairs[i][1] + opad[i]
            trans_pad.append((lo, hi))
    # grouped transpose: weight (I, O/g, *k) -> flip spatial, swap to (O, I/g, *k)
    w = jnp.flip(weight, axis=tuple(range(2, 2 + n)))
    if groups > 1:
        w = w.reshape((groups, weight.shape[0] // groups) + weight.shape[1:])
        w = jnp.swapaxes(w, 1, 2)
        w = w.reshape((weight.shape[1] * groups, weight.shape[0] // groups) + k)
    else:
        w = jnp.swapaxes(w, 0, 1)
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=(1,) * n,
        padding=trans_pad,
        lhs_dilation=strides,
        rhs_dilation=dil,
        dimension_numbers=dn,
        feature_group_count=groups,
    )
    if bias is not None:
        shape = [1] * out.ndim
        shape[1 if data_format.startswith('NC') else -1] = bias.size
        out = out + bias.reshape(shape)
    return out


def conv1d_transpose(
    x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1,
    data_format='NCL',
):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 1, data_format)


def conv2d_transpose(
    x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1,
    data_format='NCHW',
):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 2, data_format)


def conv3d_transpose(
    x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1,
    data_format='NCDHW',
):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 3, data_format)
