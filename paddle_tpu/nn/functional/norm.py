"""Normalization functionals (ref: python/paddle/nn/functional/norm.py).

Stats in fp32 regardless of input dtype (bf16-safe), results cast back.
"""
from __future__ import annotations

import jax.numpy as jnp


def _f32(x):
    return x.astype(jnp.float32)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    axes = tuple(range(x.ndim - len(normalized_shape), x.ndim))
    xf = _f32(x)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) / jnp.sqrt(var + epsilon)
    if weight is not None:
        out = out * _f32(weight)
    if bias is not None:
        out = out + _f32(bias)
    return out.astype(x.dtype)


def rms_norm(x, weight=None, epsilon=1e-6, axis=-1):
    """RMSNorm (Llama-family). Pallas-fused variant in ops/pallas/rms_norm."""
    xf = _f32(x)
    var = jnp.mean(jnp.square(xf), axis=axis, keepdims=True)
    out = xf * jnp.reciprocal(jnp.sqrt(var + epsilon))
    if weight is not None:
        out = out * _f32(weight)
    return out.astype(x.dtype)


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-5,
    data_format='NCHW',
):
    """Returns (out, new_mean, new_var) — state is explicit, the Layer
    carries it (ref semantics: nn/functional/norm.py::batch_norm)."""
    ch_axis = 1 if data_format.startswith('NC') else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    xf = _f32(x)
    if training:
        mean = jnp.mean(xf, axis=axes)
        var = jnp.var(xf, axis=axes)
        n = x.size / x.shape[ch_axis]
        unbiased = var * n / max(n - 1, 1)
        new_mean = momentum * _f32(running_mean) + (1 - momentum) * mean
        new_var = momentum * _f32(running_var) + (1 - momentum) * unbiased
    else:
        mean, var = _f32(running_mean), _f32(running_var)
        new_mean, new_var = running_mean, running_var
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]
    out = (xf - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + epsilon)
    if weight is not None:
        out = out * _f32(weight).reshape(shape)
    if bias is not None:
        out = out + _f32(bias).reshape(shape)
    return (
        out.astype(x.dtype),
        new_mean.astype(running_mean.dtype),
        new_var.astype(running_var.dtype),
    )


def instance_norm(x, weight=None, bias=None, epsilon=1e-5, data_format='NCHW'):
    ch_axis = 1 if data_format.startswith('NC') else x.ndim - 1
    axes = tuple(i for i in range(2, x.ndim)) if ch_axis == 1 else tuple(
        i for i in range(1, x.ndim - 1)
    )
    xf = _f32(x)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) / jnp.sqrt(var + epsilon)
    if weight is not None:
        shape = [1] * x.ndim
        shape[ch_axis] = x.shape[ch_axis]
        out = out * _f32(weight).reshape(shape)
        if bias is not None:
            out = out + _f32(bias).reshape(shape)
    return out.astype(x.dtype)


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5, data_format='NCHW'):
    ch_axis = 1 if data_format.startswith('NC') else x.ndim - 1
    c = x.shape[ch_axis]
    xf = _f32(x)
    if ch_axis == 1:
        g_shape = (x.shape[0], num_groups, c // num_groups) + x.shape[2:]
        axes = tuple(range(2, len(g_shape)))
    else:
        g_shape = x.shape[:-1] + (num_groups, c // num_groups)
        axes = tuple(range(1, x.ndim - 1)) + (x.ndim,)
    xg = xf.reshape(g_shape)
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - mean) / jnp.sqrt(var + epsilon)).reshape(x.shape)
    shape = [1] * x.ndim
    shape[ch_axis] = c
    if weight is not None:
        out = out * _f32(weight).reshape(shape)
    if bias is not None:
        out = out + _f32(bias).reshape(shape)
    return out.astype(x.dtype)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format='NCHW'):
    ch_axis = 1 if data_format.startswith('NC') else x.ndim - 1
    sq = jnp.square(_f32(x))
    half = size // 2
    c = x.shape[ch_axis]
    pads = [(0, 0)] * x.ndim
    pads[ch_axis] = (half, size - 1 - half)
    sq = jnp.pad(sq, pads)
    acc = 0
    for i in range(size):
        sl = [slice(None)] * x.ndim
        sl[ch_axis] = slice(i, i + c)
        acc = acc + sq[tuple(sl)]
    div = jnp.power(k + alpha * acc, beta)
    return (x / div.astype(x.dtype))
