"""Attention functionals.

`scaled_dot_product_attention` (ref: python/paddle/nn/functional/
flash_attention.py) dispatches to the pallas flash-attention TPU kernel
when available, else to a fused lax reference (same math, XLA-fused).
Layout: (batch, seq, num_heads, head_dim) — Paddle's flash-attn layout.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def _sdpa_reference(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False,
                    scale=None, rng_key=None, training=True,
                    return_probs=False):
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    scale = scale or (1.0 / math.sqrt(D))
    # GQA: broadcast kv heads if fewer than q heads
    Hk = k.shape[2]
    if Hk != H:
        rep = H // Hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qf = q.astype(jnp.float32) * scale
    logits = jnp.einsum('bqhd,bkhd->bhqk', qf, k.astype(jnp.float32))
    if is_causal:
        causal = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        logits = jnp.where(causal[None, None], logits, -1e30)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, -1e30)
        else:
            logits = logits + attn_mask.astype(jnp.float32)
    p = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0 and training:
        from ...framework import random as random_mod

        key = rng_key if rng_key is not None else random_mod.split_key()
        keep = jax.random.bernoulli(key, 1 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1 - dropout_p), 0.0)
    out = jnp.einsum('bhqk,bkhd->bqhd', p, v.astype(jnp.float32))
    return (out.astype(q.dtype), p) if return_probs else out.astype(q.dtype)


def scaled_dot_product_attention(
    query,
    key,
    value,
    attn_mask=None,
    dropout_p=0.0,
    is_causal=False,
    scale=None,
    training=True,
    rng_key=None,
    segment_ids=None,
    kv_segment_ids=None,
    window_size=None,
):
    """Flash attention on TPU; lax reference elsewhere/with masks it can't take.

    segment_ids (+optional kv_segment_ids for Sq != Sk): (B, Sq)/(B, Sk)
    int32 packed-sequence ids — attention is block-diagonal within equal
    ids (flash kernel fast path on TPU).

    window_size: optional int — causal sliding-window attention (each
    query sees only its last `window_size` keys, self included). On TPU
    this takes the flash kernel's block-skipping fast path (ref:
    python/paddle/nn/functional/flash_attention.py:1106); elsewhere the
    band folds into the mask.
    """
    from ...ops import use_pallas

    if kv_segment_ids is not None and segment_ids is None:
        raise ValueError('kv_segment_ids requires segment_ids')
    if segment_ids is not None and kv_segment_ids is None:
        if query.shape[1] != key.shape[1]:
            raise ValueError(
                'segment_ids with Sq != Sk requires kv_segment_ids')
        kv_segment_ids = segment_ids
    if window_size is not None and not is_causal:
        raise ValueError('window_size requires is_causal=True')

    use_flash = (
        dropout_p == 0.0
        and attn_mask is None
        and query.shape[-1] % 8 == 0
        and query.shape[1] >= 128
        and use_pallas()
    )
    if use_flash:
        try:
            from ...ops.pallas.flash_attention import flash_attention

            return flash_attention(query, key, value, causal=is_causal,
                                   scale=scale, segment_ids=segment_ids,
                                   kv_segment_ids=kv_segment_ids,
                                   window_size=window_size)
        except Exception as e:
            from ...ops import pallas_failed

            pallas_failed('flash_attention', e)
    if window_size is not None:
        # fold the band into the mask for the reference path
        Sq, Sk = query.shape[1], key.shape[1]
        qpos = jnp.arange(Sq)[:, None] + (Sk - Sq)
        kpos = jnp.arange(Sk)[None, :]
        band = (qpos - kpos < window_size)[None, None]    # causal half below
        if attn_mask is None:
            attn_mask = band
        elif attn_mask.dtype == jnp.bool_:
            attn_mask = attn_mask & band
        else:
            attn_mask = jnp.where(band, attn_mask.astype(jnp.float32), -1e30)
    if segment_ids is not None:
        qseg = jnp.asarray(segment_ids)
        kseg = jnp.asarray(kv_segment_ids)
        seg_mask = (qseg[:, :, None] == kseg[:, None, :])[:, None]
        if attn_mask is None:
            attn_mask = seg_mask
        elif attn_mask.dtype == jnp.bool_:
            attn_mask = attn_mask & seg_mask
        else:
            # additive float mask: masked-out pairs get -inf-like bias
            attn_mask = jnp.where(seg_mask, attn_mask, -1e30)
    out = _sdpa_reference(
        query, key, value, attn_mask, dropout_p, is_causal, scale, rng_key, training
    )
    if segment_ids is not None:
        # match the kernel's empty-segment convention: a query whose
        # segment has no kv tokens returns 0 (softmax of an all-masked
        # row would otherwise emit the uniform mean of v and leak grads)
        row_valid = jnp.any(seg_mask[:, 0], axis=-1)     # (B, Sq)
        out = jnp.where(row_valid[:, :, None, None], out, 0.0)
    return out


flash_attention = scaled_dot_product_attention


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False,
                         return_softmax=False, *, fixed_seed_offset=None,
                         rng_name='', training=True, name=None):
    """Packed-QKV flash attention (ref: nn/functional/flash_attention.py::
    flash_attn_qkvpacked). qkv: (B, S, 3, H, D). Returns (out, softmax) —
    softmax is None unless requested (and requesting it forces the
    non-flash path, as the reference's kernel does for its debug mode)."""
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    if return_softmax:
        return _sdpa_reference(q, k, v, dropout_p=dropout, is_causal=causal,
                               training=training, return_probs=True)
    out = scaled_dot_product_attention(q, k, v, dropout_p=dropout,
                                       is_causal=causal, training=training)
    return out, None


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q, max_seqlen_k, scale,
                                dropout=0.0, causal=False,
                                return_softmax=False, fixed_seed_offset=None,
                                rng_name='', varlen_padded=True,
                                training=True, name=None):
    """Varlen packed flash attention (ref: flash_attention.py::
    flash_attn_varlen_qkvpacked). qkv: (total_tokens, 3, H, D) with
    cumulative sequence boundaries `cu_seqlens_*`.

    TPU-native mapping: the token stream is ONE long row and the varlen
    boundaries become segment ids — exactly the packed-sequence fast path
    the pallas flash kernel already supports (block-diagonal masking),
    so no unpadding/repadding round-trip is needed.
    """
    total, _, h, d = qkv.shape
    q = qkv[None, :, 0]
    k = qkv[None, :, 1]
    v = qkv[None, :, 2]
    positions = jnp.arange(total)
    seg_q = jnp.searchsorted(jnp.asarray(cu_seqlens_q)[1:], positions,
                             side='right').astype(jnp.int32)[None]
    if return_softmax:  # debug mode: dense block-diagonal probabilities
        seg_mask = (seg_q[:, :, None] == seg_q[:, None, :])[:, None]
        out, p = _sdpa_reference(q, k, v, attn_mask=seg_mask,
                                 dropout_p=dropout, is_causal=causal,
                                 scale=scale, training=training,
                                 return_probs=True)
        return out[0], p[0]
    out = scaled_dot_product_attention(
        q, k, v, dropout_p=dropout, is_causal=causal, scale=scale,
        training=training, segment_ids=seg_q)
    return out[0], None


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=False, window_size=None,
                        fixed_seed_offset=None, rng_name='', training=True,
                        name=None):
    """FlashMask attention (ref: flash_attention.py::flashmask_attention).

    `startend_row_indices` (B, H|1, Sk, 1|2|4) encodes column-wise sparse
    masks: with 1 value LTS (causal: rows >= LTS masked), 2 values
    [LTS, LTE) masked below the diagonal, 4 values
    [LTS, LTE) ∪ [UTS, UTE) for bidirectional. This implementation lowers
    the encoding to a boolean mask consumed by the fused attention path —
    the row-index compression is a CUDA-kernel memory optimisation; under
    XLA the mask fuses into the attention einsum anyway.
    """
    b, sq, h, d = query.shape
    sk = key.shape[1]
    rows = jnp.arange(sq)[:, None]                      # query index
    if startend_row_indices is None:
        mask = None
    else:
        idx = jnp.asarray(startend_row_indices)         # (B, Hm, Sk, C)
        c = idx.shape[-1]
        idx = idx.transpose(0, 1, 3, 2)[:, :, :, None, :]  # (B,Hm,C,1,Sk)
        if causal:
            if c == 1:
                lts = idx[:, :, 0]
                mask = rows < lts                        # keep rows < LTS
            elif c == 2:
                lts, lte = idx[:, :, 0], idx[:, :, 1]
                mask = (rows < lts) | (rows >= lte)
            else:
                raise ValueError(f'causal flashmask expects 1 or 2 values, '
                                 f'got {c}')
        else:
            if c == 2:
                lts, ute = idx[:, :, 0], idx[:, :, 1]
                mask = (rows < lts) & (rows >= ute)
            elif c == 4:
                lts, lte = idx[:, :, 0], idx[:, :, 1]
                uts, ute = idx[:, :, 2], idx[:, :, 3]
                mask = ~(((rows >= lts) & (rows < lte))
                         | ((rows >= uts) & (rows < ute)))
            else:
                raise ValueError(f'non-causal flashmask expects 2 or 4 '
                                 f'values, got {c}')
    if window_size is not None:
        w = (window_size, window_size) if isinstance(window_size, int) \
            else tuple(window_size)
        cols = jnp.arange(sk)[None, :]
        win = (rows - cols <= w[0]) & (cols - rows <= w[1])
        mask = win[None, None] if mask is None else mask & win[None, None]
    out = scaled_dot_product_attention(
        query, key, value, attn_mask=mask, dropout_p=dropout,
        is_causal=causal, training=training)
    if mask is not None:
        # same empty-row convention as the segment-masked kernels: a query
        # whose every key is masked returns 0, not the uniform mean of v
        eff = mask
        if causal:
            cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
            eff = eff & cm[None, None]
        row_valid = jnp.any(eff, axis=-1)                # (B, Hm, Sq)
        out = jnp.where(
            jnp.moveaxis(row_valid, 1, -1)[..., None], out, 0.0)
    return out


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None):
    """CSR-patterned sparse attention (ref: nn/functional/
    sparse_attention.py; the reference requires CUDA 11.3+). q/k/v:
    (B, H, S, D); offset (B, H, S+1); columns (B, H, nnz).

    On TPU the CSR pattern is lowered to a boolean mask and fused into
    the dense attention — XLA's MXU tiling beats gather-based sparse
    matmul until sparsity is extreme, and the semantics (softmax only
    over the listed columns) are preserved exactly.
    """
    b, h, s, d = query.shape
    nnz = sparse_csr_columns.shape[-1]

    def one_head(offset, columns):
        row_of = jnp.searchsorted(offset, jnp.arange(nnz), side='right') - 1
        m = jnp.zeros((s, s), bool)
        return m.at[row_of, columns].set(True)

    mask = jax.vmap(jax.vmap(one_head))(
        jnp.asarray(sparse_csr_offset), jnp.asarray(sparse_csr_columns))
    if key_padding_mask is not None:
        mask = mask & (jnp.asarray(key_padding_mask) != 0)[:, None, None, :]
    if attn_mask is not None:
        mask = mask & (jnp.asarray(attn_mask) != 0)[None, None]
    qt = query.transpose(0, 2, 1, 3)    # -> (B, S, H, D) sdpa layout
    kt = key.transpose(0, 2, 1, 3)
    vt = value.transpose(0, 2, 1, 3)
    out = scaled_dot_product_attention(qt, kt, vt, attn_mask=mask)
    return out.transpose(0, 2, 1, 3)
