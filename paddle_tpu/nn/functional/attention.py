"""Attention functionals.

`scaled_dot_product_attention` (ref: python/paddle/nn/functional/
flash_attention.py) dispatches to the pallas flash-attention TPU kernel
when available, else to a fused lax reference (same math, XLA-fused).
Layout: (batch, seq, num_heads, head_dim) — Paddle's flash-attn layout.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def _sdpa_reference(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False, scale=None, rng_key=None, training=True):
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    scale = scale or (1.0 / math.sqrt(D))
    # GQA: broadcast kv heads if fewer than q heads
    Hk = k.shape[2]
    if Hk != H:
        rep = H // Hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qf = q.astype(jnp.float32) * scale
    logits = jnp.einsum('bqhd,bkhd->bhqk', qf, k.astype(jnp.float32))
    if is_causal:
        causal = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        logits = jnp.where(causal[None, None], logits, -1e30)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, -1e30)
        else:
            logits = logits + attn_mask.astype(jnp.float32)
    p = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0 and training:
        from ...framework import random as random_mod

        key = rng_key if rng_key is not None else random_mod.split_key()
        keep = jax.random.bernoulli(key, 1 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1 - dropout_p), 0.0)
    out = jnp.einsum('bhqk,bkhd->bqhd', p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def scaled_dot_product_attention(
    query,
    key,
    value,
    attn_mask=None,
    dropout_p=0.0,
    is_causal=False,
    scale=None,
    training=True,
    rng_key=None,
    segment_ids=None,
    kv_segment_ids=None,
):
    """Flash attention on TPU; lax reference elsewhere/with masks it can't take.

    segment_ids (+optional kv_segment_ids for Sq != Sk): (B, Sq)/(B, Sk)
    int32 packed-sequence ids — attention is block-diagonal within equal
    ids (flash kernel fast path on TPU).
    """
    from ...ops import use_pallas

    if kv_segment_ids is not None and segment_ids is None:
        raise ValueError('kv_segment_ids requires segment_ids')
    if segment_ids is not None and kv_segment_ids is None:
        if query.shape[1] != key.shape[1]:
            raise ValueError(
                'segment_ids with Sq != Sk requires kv_segment_ids')
        kv_segment_ids = segment_ids

    use_flash = (
        dropout_p == 0.0
        and attn_mask is None
        and query.shape[-1] % 8 == 0
        and query.shape[1] >= 128
        and use_pallas()
    )
    if use_flash:
        try:
            from ...ops.pallas.flash_attention import flash_attention

            return flash_attention(query, key, value, causal=is_causal,
                                   scale=scale, segment_ids=segment_ids,
                                   kv_segment_ids=kv_segment_ids)
        except Exception as e:
            import warnings

            warnings.warn(f'pallas flash attention unavailable, using lax '
                          f'reference: {e!r}', stacklevel=2)
    if segment_ids is not None:
        qseg = jnp.asarray(segment_ids)
        kseg = jnp.asarray(kv_segment_ids)
        seg_mask = (qseg[:, :, None] == kseg[:, None, :])[:, None]
        if attn_mask is None:
            attn_mask = seg_mask
        elif attn_mask.dtype == jnp.bool_:
            attn_mask = attn_mask & seg_mask
        else:
            # additive float mask: masked-out pairs get -inf-like bias
            attn_mask = jnp.where(seg_mask, attn_mask, -1e30)
    out = _sdpa_reference(
        query, key, value, attn_mask, dropout_p, is_causal, scale, rng_key, training
    )
    if segment_ids is not None:
        # match the kernel's empty-segment convention: a query whose
        # segment has no kv tokens returns 0 (softmax of an all-masked
        # row would otherwise emit the uniform mean of v and leak grads)
        row_valid = jnp.any(seg_mask[:, 0], axis=-1)     # (B, Sq)
        out = jnp.where(row_valid[:, :, None, None], out, 0.0)
    return out


flash_attention = scaled_dot_product_attention
