"""nn.functional namespace (ref: python/paddle/nn/functional/__init__.py)."""
from .activation import *  # noqa: F401,F403
from .attention import (  # noqa: F401
    flash_attn_qkvpacked,
    flash_attn_varlen_qkvpacked,
    flashmask_attention,
    scaled_dot_product_attention,
    sparse_attention,
)
# import ORDER matters: pulling the names from the submodule registers
# `nn.functional.flash_attention` as an importable module path (ref
# scripts do `from paddle.nn.functional.flash_attention import ...`)
# while the from-import keeps the attribute bound to the FUNCTION
from .flash_attention import (  # noqa: F401
    calc_reduced_attention_scores,
    flash_attention,
    flash_attn_unpadded,
    sdp_kernel,
)
from .common import *  # noqa: F401,F403
from .conv import (  # noqa: F401
    conv1d,
    conv1d_transpose,
    conv2d,
    conv2d_transpose,
    conv3d,
    conv3d_transpose,
)
from .loss import *  # noqa: F401,F403
from .norm import (  # noqa: F401
    batch_norm,
    group_norm,
    instance_norm,
    layer_norm,
    local_response_norm,
    rms_norm,
)
from .pooling import *  # noqa: F401,F403
from .pooling import (  # noqa: F401
    fractional_max_pool2d,
    fractional_max_pool3d,
    lp_pool1d,
    max_unpool1d,
    max_unpool2d,
    max_unpool3d,
)
from .vision import (  # noqa: F401
    affine_grid,
    channel_shuffle,
    gather_tree,
    grid_sample,
    sequence_mask,
    temporal_shift,
)
