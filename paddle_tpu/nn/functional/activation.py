"""Activation functionals (ref: python/paddle/nn/functional/activation.py).

All map to single XLA HLO ops or small fusable expressions — the VPU
handles these; XLA fuses them into surrounding matmuls.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def relu(x):
    return jax.nn.relu(x)


def relu6(x):
    return jnp.clip(x, 0, 6)


def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


def silu(x):
    return jax.nn.silu(x)


def swish(x):
    return jax.nn.silu(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


def tanh(x):
    return jnp.tanh(x)


def tanhshrink(x):
    return x - jnp.tanh(x)


def softmax(x, axis=-1, dtype=None):
    if dtype is not None:
        x = x.astype(dtype)
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis=-1, dtype=None):
    if dtype is not None:
        x = x.astype(dtype)
    return jax.nn.log_softmax(x, axis=axis)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    from ...framework import random as random_mod

    g = jax.random.gumbel(random_mod.split_key(), x.shape, dtype=x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        y_hard = jnp.zeros_like(y)
        y_hard = jnp.take_along_axis(y_hard, idx, axis=axis) * 0 + 1
        onehot = jax.nn.one_hot(
            jnp.argmax(y, axis=axis), y.shape[axis], axis=axis, dtype=y.dtype
        )
        y = jax.lax.stop_gradient(onehot - y) + y
    return y


def leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope)


def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


def celu(x, alpha=1.0):
    return jax.nn.celu(x, alpha)


def hardswish(x):
    return x * jnp.clip(x + 3, 0, 6) / 6


def hardsigmoid(x, slope=1 / 6, offset=0.5):
    return jnp.clip(x * slope + offset, 0, 1)


def hardtanh(x, min=-1.0, max=1.0):
    return jnp.clip(x, min, max)


def hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0)


def softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold, jnp.where(x < -threshold, x + threshold, 0))


def softplus(x, beta=1.0, threshold=20.0):
    return jnp.where(x * beta > threshold, x, jax.nn.softplus(x * beta) / beta)


def softsign(x):
    return jax.nn.soft_sign(x)


def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def prelu(x, weight, data_format='NCHW'):
    if weight.size > 1:
        shape = [1] * x.ndim
        ch_axis = 1 if data_format == 'NCHW' else x.ndim - 1
        shape[ch_axis] = weight.size
        weight = weight.reshape(shape)
    return jnp.where(x > 0, x, weight * x)


def rrelu(x, lower=1 / 8.0, upper=1 / 3.0, training=True):
    from ...framework import random as random_mod

    if training:
        a = jax.random.uniform(
            random_mod.split_key(), x.shape, dtype=x.dtype, minval=lower, maxval=upper
        )
    else:
        a = (lower + upper) / 2
    return jnp.where(x >= 0, x, a * x)


def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


def maxout(x, groups, axis=1):
    c = x.shape[axis]
    new_shape = list(x.shape)
    new_shape[axis] = c // groups
    new_shape.insert(axis + 1, groups)
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


def thresholded_relu(x, threshold=1.0, value=0.0):
    return jnp.where(x > threshold, x, value)


def swiglu(x, y=None):
    """SwiGLU gate (used by Llama FFN); fuses on TPU into two matmuls + VPU."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


# The reference's in-place variants (relu_ etc. mutate their input). jax
# arrays are immutable, so these are aliases of the pure ops — matching
# the reference's *return value*, which is how downstream code uses them.
relu_ = relu
tanh_ = tanh
elu_ = elu
hardtanh_ = hardtanh
leaky_relu_ = leaky_relu
softmax_ = softmax
thresholded_relu_ = thresholded_relu
