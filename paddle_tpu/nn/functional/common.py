"""Common functionals: linear, dropout, embedding, interpolate, etc.
(ref: python/paddle/nn/functional/common.py, input.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework import random as random_mod
from ...tensor.manipulation import pad  # noqa: F401  (re-exported)


def linear(x, weight, bias=None):
    """y = x @ W + b with W stored (in_features, out_features) as Paddle does
    (ref: nn/functional/common.py::linear) — this is also the MXU-friendly
    layout (no transpose needed)."""
    # operator form, not jnp.matmul: jax defers `@` to __rmatmul__ for
    # non-array weights, which is how QuantizedWeight serves Linear
    y = x @ weight
    if bias is not None:
        y = y + bias
    return y


def embedding(x, weight, padding_idx=None, sparse=False):
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return out


def one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes)


def dropout(x, p=0.5, axis=None, training=True, mode='upscale_in_train', rng_key=None):
    if not training or p == 0:
        if mode == 'downscale_in_infer' and not training:
            return x * (1 - p)
        return x
    key = rng_key if rng_key is not None else random_mod.split_key()
    shape = list(x.shape)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        shape = [s if i in axes else 1 for i, s in enumerate(shape)]
    keep = jax.random.bernoulli(key, 1 - p, tuple(shape))
    if mode == 'upscale_in_train':
        return jnp.where(keep, x / (1 - p), 0)
    return jnp.where(keep, x, 0)


def dropout2d(x, p=0.5, training=True, data_format='NCHW', rng_key=None):
    axis = [0, 1] if data_format == 'NCHW' else [0, 3]
    return dropout(x, p, axis=axis, training=training, rng_key=rng_key)


def dropout3d(x, p=0.5, training=True, data_format='NCDHW', rng_key=None):
    axis = [0, 1] if data_format == 'NCDHW' else [0, 4]
    return dropout(x, p, axis=axis, training=training, rng_key=rng_key)


def alpha_dropout(x, p=0.5, training=True, rng_key=None):
    if not training or p == 0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = rng_key if rng_key is not None else random_mod.split_key()
    keep = jax.random.bernoulli(key, 1 - p, x.shape)
    a = (1 / jnp.sqrt((1 - p) * (1 + p * alpha_p**2))).astype(x.dtype)
    b = -a * alpha_p * p
    return a * jnp.where(keep, x, alpha_p) + b


def feature_alpha_dropout(x, p=0.5, training=True):
    return alpha_dropout(x, p, training)


def bilinear(x1, x2, weight, bias=None):
    # weight: (out, in1, in2)
    y = jnp.einsum('bi,oij,bj->bo', x1, weight, x2)
    if bias is not None:
        y = y + bias
    return y


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.clip(n1 * n2, eps, None)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False):
    d = x - y + epsilon
    return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)


def normalize(x, p=2, axis=1, epsilon=1e-12):
    n = jnp.linalg.norm(x, ord=p, axis=axis, keepdims=True)
    return x / jnp.clip(n, epsilon, None)


def interpolate(
    x,
    size=None,
    scale_factor=None,
    mode='nearest',
    align_corners=False,
    data_format='NCHW',
):
    """ref: nn/functional/common.py::interpolate. Implemented with
    jax.image.resize (gather-based, TPU friendly)."""
    chan_last = data_format in ('NHWC', 'NDHWC', 'NLC')
    spatial = x.ndim - 2
    if chan_last:
        sp_shape = x.shape[1:-1]
    else:
        sp_shape = x.shape[2:]
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * spatial
        size = [int(s * f) for s, f in zip(sp_shape, scale_factor)]
    size = [int(s) for s in size]
    if chan_last:
        new_shape = (x.shape[0], *size, x.shape[-1])
    else:
        new_shape = (x.shape[0], x.shape[1], *size)
    method = {
        'nearest': 'nearest',
        'bilinear': 'bilinear',
        'trilinear': 'trilinear',
        'linear': 'linear',
        'bicubic': 'bicubic',
        'area': 'linear',
    }[mode]
    if mode == 'nearest' or not align_corners:
        return jax.image.resize(x, new_shape, method=method)
    # align_corners path via explicit coordinate map
    return _resize_align_corners(x, new_shape, method, chan_last)


def _resize_align_corners(x, new_shape, method, chan_last):
    import numpy as np

    sp_axes = list(range(1, x.ndim - 1)) if chan_last else list(range(2, x.ndim))
    out = x
    for ax in sp_axes:
        n_in, n_out = x.shape[ax], new_shape[ax]
        if n_in == n_out:
            continue
        if n_out == 1:
            idx = jnp.zeros((1,))
        else:
            idx = jnp.linspace(0, n_in - 1, n_out)
        lo = jnp.floor(idx).astype(jnp.int32)
        hi = jnp.clip(lo + 1, 0, n_in - 1)
        w = (idx - lo).astype(x.dtype)
        shape = [1] * out.ndim
        shape[ax] = n_out
        w = w.reshape(shape)
        out = jnp.take(out, lo, axis=ax) * (1 - w) + jnp.take(out, hi, axis=ax) * w
    return out


def upsample(x, size=None, scale_factor=None, mode='nearest', align_corners=False, data_format='NCHW'):
    return interpolate(x, size, scale_factor, mode, align_corners, data_format)


def pixel_shuffle(x, upscale_factor, data_format='NCHW'):
    r = upscale_factor
    if data_format == 'NCHW':
        b, c, h, w = x.shape
        x = x.reshape(b, c // (r * r), r, r, h, w)
        x = x.transpose(0, 1, 4, 2, 5, 3)
        return x.reshape(b, c // (r * r), h * r, w * r)
    b, h, w, c = x.shape
    x = x.reshape(b, h, w, r, r, c // (r * r))
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h * r, w * r, c // (r * r))


def pixel_unshuffle(x, downscale_factor, data_format='NCHW'):
    r = downscale_factor
    if data_format == 'NCHW':
        b, c, h, w = x.shape
        x = x.reshape(b, c, h // r, r, w // r, r)
        x = x.transpose(0, 1, 3, 5, 2, 4)
        return x.reshape(b, c * r * r, h // r, w // r)
    b, h, w, c = x.shape
    x = x.reshape(b, h // r, r, w // r, r, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // r, w // r, c * r * r)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    """im2col (ref: nn/functional/common.py::unfold). NCHW input."""
    ks = [kernel_sizes] * 2 if isinstance(kernel_sizes, int) else list(kernel_sizes)
    st = [strides] * 2 if isinstance(strides, int) else list(strides)
    pd = [paddings] * 2 if isinstance(paddings, int) else list(paddings)
    dl = [dilations] * 2 if isinstance(dilations, int) else list(dilations)
    b, c, h, w = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])))
    patches = jax.lax.conv_general_dilated_patches(
        x,
        filter_shape=ks,
        window_strides=st,
        padding='VALID',
        rhs_dilation=dl,
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'),
    )
    return patches.reshape(b, c * ks[0] * ks[1], -1)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    ks = [kernel_sizes] * 2 if isinstance(kernel_sizes, int) else list(kernel_sizes)
    st = [strides] * 2 if isinstance(strides, int) else list(strides)
    pd = [paddings] * 2 if isinstance(paddings, int) else list(paddings)
    b, ckk, L = x.shape
    c = ckk // (ks[0] * ks[1])
    H, W = output_sizes
    oh = (H + 2 * pd[0] - ks[0]) // st[0] + 1
    ow = (W + 2 * pd[1] - ks[1]) // st[1] + 1
    out = jnp.zeros((b, c, H + 2 * pd[0], W + 2 * pd[1]), x.dtype)
    x = x.reshape(b, c, ks[0], ks[1], oh, ow)
    for i in range(ks[0]):
        for j in range(ks[1]):
            out = out.at[:, :, i : i + oh * st[0] : st[0], j : j + ow * st[1] : st[1]].add(
                x[:, :, i, j]
            )
    if pd[0] or pd[1]:
        out = out[:, :, pd[0] : out.shape[2] - pd[0], pd[1] : out.shape[3] - pd[1]]
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1):
    k = label.shape[-1]
    if prior_dist is None:
        return (1 - epsilon) * label + epsilon / k
    return (1 - epsilon) * label + epsilon * prior_dist


def class_center_sample(label, num_classes, num_samples, group=None):
    """ref: nn/functional/common.py::class_center_sample (PartialFC,
    arXiv:2010.05222) — keep every positive class center, fill up to
    ``num_samples`` with uniformly sampled negatives, and remap labels
    into the sampled index space.

    Host-side (eager) op: sampling belongs in the data/step-setup path,
    and the output length is data-dependent (all positives kept when
    they exceed num_samples), which jit's static shapes cannot express.
    Returns (remapped_label (N,), sampled_class_center (M,)), integer
    dtype (int64 when jax_enable_x64 is on, int32 otherwise).
    """
    import numpy as np

    if group not in (None, False):
        # the reference's distributed PartialFC samples per model-parallel
        # rank over a process group; here the sharded-classes story lives
        # in margin_cross_entropy(group=<mesh axis>) — sampling locally
        # against the global class space would silently disagree with it
        raise NotImplementedError(
            'class_center_sample(group=...) is not supported: sample '
            'locally (group=None) and use margin_cross_entropy(group='
            '<mesh axis>) for sharded class centers')
    if num_samples > num_classes:
        raise ValueError(
            f'num_samples ({num_samples}) cannot exceed num_classes '
            f'({num_classes})')
    lab = np.asarray(label).astype(np.int64).reshape(-1)
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        neg_pool = np.setdiff1d(np.arange(num_classes, dtype=np.int64), pos,
                                assume_unique=True)
        key = random_mod.split_key()
        order = np.asarray(
            jax.random.permutation(key, neg_pool.shape[0]))
        need = num_samples - len(pos)
        sampled = np.sort(np.concatenate([pos, neg_pool[order[:need]]]))
    # remap: position of each label within the sampled (sorted) centers
    remapped = np.searchsorted(sampled, lab)
    return jnp.asarray(remapped), jnp.asarray(sampled)


def zeropad2d(x, padding, data_format='NCHW'):
    """Zero-pad H/W of a 4-D tensor; padding = [left, right, top, bottom]
    (ref: nn/functional/common.py::zeropad2d)."""
    l, r, t, b = [int(p) for p in padding]
    if data_format == 'NCHW':
        widths = [(0, 0), (0, 0), (t, b), (l, r)]
    elif data_format == 'NHWC':
        widths = [(0, 0), (t, b), (l, r), (0, 0)]
    else:
        raise ValueError(f'bad data_format: {data_format}')
    return jnp.pad(x, widths)
