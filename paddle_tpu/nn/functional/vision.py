"""Vision functionals: affine_grid, grid_sample, channel_shuffle,
temporal_shift, sequence_mask (ref: python/paddle/nn/functional/vision.py,
extension.py).

TPU notes: grid_sample is a gather-heavy op that XLA lowers to dynamic
gathers — all shapes here are static, the 2^ndim corner loop is unrolled
in Python (ndim is 2 or 3, known at trace time), and the per-corner
weights fuse into the gather epilogue. No data-dependent control flow.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _unnormalize(coord, size, align_corners):
    """[-1, 1] grid coordinate -> pixel coordinate."""
    if align_corners:
        return (coord + 1) * 0.5 * (size - 1)
    return ((coord + 1) * size - 1) * 0.5


def _reflect(coord, size, align_corners):
    """Reflect out-of-range pixel coordinates back into the valid range
    (padding_mode='reflection'; boundary behaviour matches the reference:
    reflection axes at pixel centers when align_corners else at edges)."""
    if size == 1:
        return jnp.zeros_like(coord)
    if align_corners:
        span = size - 1
        coord = jnp.abs(coord) % (2 * span)
        return jnp.where(coord > span, 2 * span - coord, coord)
    span = size
    coord = jnp.abs(coord + 0.5) % (2 * span)
    coord = jnp.where(coord > span, 2 * span - coord, coord) - 0.5
    return jnp.clip(coord, 0, size - 1)


def grid_sample(x, grid, mode='bilinear', padding_mode='zeros',
                align_corners=True):
    """Sample `x` at the flow-field `grid` locations.

    x: (N, C, H, W) or (N, C, D, H, W); grid: (N, H_out, W_out, 2) or
    (N, D_out, H_out, W_out, 3) with coordinates in [-1, 1] ordered
    (x, y[, z]) — x indexes the *last* (width) axis, matching the
    reference (ref: nn/functional/vision.py::grid_sample).
    """
    if mode not in ('bilinear', 'nearest'):
        raise ValueError(f"mode must be 'bilinear' or 'nearest', got {mode}")
    if padding_mode not in ('zeros', 'border', 'reflection'):
        raise ValueError(f"bad padding_mode: {padding_mode}")
    ndim = x.ndim - 2  # spatial rank: 2 or 3
    if grid.ndim != x.ndim or grid.shape[-1] != ndim:
        raise ValueError(f'grid shape {grid.shape} does not match x {x.shape}')
    sizes = x.shape[2:]                       # (H, W) or (D, H, W)
    out_spatial = grid.shape[1:-1]
    compute_dtype = jnp.promote_types(x.dtype, jnp.float32)

    # Per-axis pixel coordinates. grid's last dim is (x, y[, z]) =
    # (w, h[, d]) — reverse it to match the spatial-dims order of `x`.
    coords = []
    for axis in range(ndim):
        c = _unnormalize(grid[..., ndim - 1 - axis].astype(compute_dtype),
                         sizes[axis], align_corners)
        if padding_mode == 'border':
            c = jnp.clip(c, 0, sizes[axis] - 1)
        elif padding_mode == 'reflection':
            c = _reflect(c, sizes[axis], align_corners)
        coords.append(c)

    x_flat = x.reshape(x.shape[0], x.shape[1], -1)  # (N, C, prod(sizes))

    def _gather(idx_list, weight):
        """Gather x at integer per-axis indices, weighting by `weight`
        and zeroing out-of-bounds taps (padding_mode='zeros')."""
        valid = None
        flat = 0
        for axis, idx in enumerate(idx_list):
            if padding_mode == 'zeros':
                ok = (idx >= 0) & (idx <= sizes[axis] - 1)
                valid = ok if valid is None else (valid & ok)
            idx = jnp.clip(idx, 0, sizes[axis] - 1)
            flat = flat * sizes[axis] + idx
        vals = jax.vmap(lambda xf, ix: jnp.take(xf, ix.ravel(), axis=1)
                        )(x_flat, flat)           # (N, C, prod(out))
        vals = vals.reshape(x.shape[0], x.shape[1], *out_spatial)
        if valid is not None:
            weight = weight * valid.astype(compute_dtype)
        return vals * weight[:, None]

    if mode == 'nearest':
        idx = [jnp.round(c).astype(jnp.int32) for c in coords]
        out = _gather(idx, jnp.ones(grid.shape[:-1], compute_dtype))
    else:
        lo = [jnp.floor(c) for c in coords]
        frac = [c - l for c, l in zip(coords, lo)]
        lo = [l.astype(jnp.int32) for l in lo]
        out = 0
        for corner in range(2 ** ndim):  # unrolled: 4 (2-D) or 8 (3-D) taps
            bits = [(corner >> a) & 1 for a in range(ndim)]
            idx = [l + b for l, b in zip(lo, bits)]
            w = 1.0
            for f, b in zip(frac, bits):
                w = w * (f if b else (1 - f))
            out = out + _gather(idx, w)
    return out.astype(x.dtype)


def affine_grid(theta, out_shape, align_corners=True):
    """Generate a sampling grid from batched affine matrices.

    theta: (N, 2, 3) with out_shape [N, C, H, W] -> grid (N, H, W, 2); or
    (N, 3, 4) with out_shape [N, C, D, H, W] -> grid (N, D, H, W, 3)
    (ref: nn/functional/vision.py::affine_grid).
    """
    out_shape = [int(s) for s in out_shape]
    ndim = len(out_shape) - 2
    if theta.shape[-2:] != (ndim, ndim + 1):
        raise ValueError(f'theta {theta.shape} does not match out_shape '
                         f'{out_shape}')
    spatial = out_shape[2:]
    dtype = theta.dtype

    def _base(size):
        if align_corners:
            return (jnp.linspace(-1.0, 1.0, size, dtype=dtype) if size > 1
                    else jnp.zeros((1,), dtype))
        return (2 * jnp.arange(size, dtype=dtype) + 1) / size - 1

    # Homogeneous base coordinates ordered (x=w, y=h[, z=d], 1).
    axes = [_base(s) for s in spatial]
    mesh = jnp.meshgrid(*axes, indexing='ij')     # each (*spatial,)
    base = jnp.stack(list(reversed(mesh)) + [jnp.ones(spatial, dtype)],
                     axis=-1)                     # (*spatial, ndim+1)
    # (N, *spatial, ndim): one matmul per batch — fine for the MXU.
    return jnp.einsum('...i,nji->n...j', base, theta)


def channel_shuffle(x, groups, data_format='NCHW'):
    """Rearrange channels by transposing the (groups, C//groups) split
    (ref: nn/functional/vision.py::channel_shuffle)."""
    if data_format not in ('NCHW', 'NHWC'):
        raise ValueError(f'bad data_format: {data_format}')
    c_axis = 1 if data_format == 'NCHW' else x.ndim - 1
    c = x.shape[c_axis]
    if c % groups:
        raise ValueError(f'channels {c} not divisible by groups {groups}')
    shape = list(x.shape)
    split = shape[:c_axis] + [groups, c // groups] + shape[c_axis + 1:]
    perm = list(range(len(split)))
    perm[c_axis], perm[c_axis + 1] = perm[c_axis + 1], perm[c_axis]
    return x.reshape(split).transpose(perm).reshape(shape)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format='NCHW'):
    """Shift a ratio of channels one step along the temporal axis
    (ref: nn/functional/extension.py::temporal_shift). x: (N*T, C, H, W)
    or (N*T, H, W, C); the first `shift_ratio*C` channels take their value
    from t+1, the next `shift_ratio*C` from t-1, the rest pass through."""
    if data_format not in ('NCHW', 'NHWC'):
        raise ValueError(f'bad data_format: {data_format}')
    nchw = data_format == 'NCHW'
    nt = x.shape[0]
    if nt % seg_num:
        raise ValueError(f'batch {nt} not divisible by seg_num {seg_num}')
    c = x.shape[1] if nchw else x.shape[-1]
    xt = x.reshape((nt // seg_num, seg_num) + x.shape[1:])  # (N, T, ...)
    c_axis = 2 if nchw else xt.ndim - 1
    c1 = int(c * shift_ratio)
    c2 = 2 * c1

    def _chan(lo, hi):
        sl = [slice(None)] * xt.ndim
        sl[c_axis] = slice(lo, hi)
        return xt[tuple(sl)]

    def _tshift(seg, direction):
        # direction +1: value from t+1 (pad at the end); -1: from t-1.
        pad = [(0, 0)] * seg.ndim
        pad[1] = (0, 1) if direction > 0 else (1, 0)
        padded = jnp.pad(seg, pad)
        return (padded[:, 1:] if direction > 0 else padded[:, :-1])

    out = jnp.concatenate(
        [_tshift(_chan(0, c1), +1), _tshift(_chan(c1, c2), -1),
         _chan(c2, None)], axis=c_axis)
    return out.reshape(x.shape)


def sequence_mask(x, maxlen=None, dtype='int64'):
    """Length tensor -> boolean-style mask: out[..., j] = j < x[...]
    (ref: nn/functional/extension.py::sequence_mask). `maxlen` must be
    static under jit (defaults to max(x) eagerly)."""
    if maxlen is None:
        maxlen = int(jnp.max(x))
    steps = jnp.arange(maxlen, dtype=jnp.int64 if x.dtype == jnp.int64
                       else jnp.int32)
    return (steps < x[..., None]).astype(dtype)


def gather_tree(ids, parents):
    """Reconstruct beam-search token paths from per-step ids and parent
    beam indices (ref: nn/functional/extension.py::gather_tree). Shapes
    (max_time, batch, beam). A reverse `lax.scan` follows parent pointers
    from the last step — the backtrace every beam decoder needs."""
    ids = jnp.asarray(ids)
    parents = jnp.asarray(parents)
    if ids.ndim != 3:
        raise ValueError(f'gather_tree expects (time, batch, beam), '
                         f'got {ids.shape}')
    beam = ids.shape[-1]

    def step(beam_idx, inp):
        step_ids, step_parents = inp
        tok = jnp.take_along_axis(step_ids, beam_idx, axis=-1)
        nxt = jnp.take_along_axis(step_parents, beam_idx, axis=-1)
        return nxt, tok

    init = jnp.broadcast_to(jnp.arange(beam)[None], ids.shape[1:])
    _, toks = jax.lax.scan(step, init, (ids, parents), reverse=True)
    return toks
