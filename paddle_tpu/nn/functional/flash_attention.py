"""Importable flash-attention module path.

ref: python/paddle/nn/functional/flash_attention.py — scripts do
``from paddle.nn.functional.flash_attention import flash_attention``;
this module provides that path with the reference signatures (the
compute dispatches to the pallas TPU kernel via
scaled_dot_product_attention, lax reference elsewhere).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .attention import (  # noqa: F401
    flash_attn_qkvpacked,
    flash_attn_varlen_qkvpacked,
    flashmask_attention,
    scaled_dot_product_attention,
)

__all__ = [
    'flash_attention',
    'flash_attn_qkvpacked',
    'flash_attn_unpadded',
    'flash_attn_varlen_qkvpacked',
    'flashmask_attention',
    'scaled_dot_product_attention',
    'sdp_kernel',
    'calc_reduced_attention_scores',
]


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, *, fixed_seed_offset=None,
                    rng_name='', training=True, name=None):
    """ref: flash_attention.py::flash_attention — (B, S, H, D) inputs,
    returns (out, softmax) where softmax is None unless requested."""
    out = scaled_dot_product_attention(
        query, key, value, dropout_p=dropout if training else 0.0,
        is_causal=causal)
    softmax = None
    if return_softmax:
        d = query.shape[-1]
        s = jnp.einsum('bqhd,bkhd->bhqk',
                       query.astype(jnp.float32),
                       key.astype(jnp.float32)) / jnp.sqrt(
                           jnp.asarray(d, jnp.float32))
        if causal:
            Sq, Sk = query.shape[1], key.shape[1]
            mask = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
            s = jnp.where(mask, s, -jnp.inf)
        softmax = jax.nn.softmax(s, axis=-1).astype(query.dtype)
    return out, softmax


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False, *,
                        fixed_seed_offset=None, rng_name='', training=True,
                        name=None):
    """ref: flash_attention.py::flash_attn_unpadded — packed varlen
    attention: (total_tokens, H, D) inputs, sequences delimited by
    cu_seqlens. Mapped to segment-masked sdpa (block-diagonal within
    each sequence — the flash kernel's packed fast path on TPU)."""
    tq = query.shape[0]
    tk = key.shape[0]

    def seg_ids(total, cu):
        # token i belongs to the sequence whose [cu[j], cu[j+1]) covers i
        return (jnp.searchsorted(jnp.asarray(cu), jnp.arange(total),
                                 side='right') - 1).astype(jnp.int32)

    q_seg = seg_ids(tq, cu_seqlens_q)[None]
    k_seg = seg_ids(tk, cu_seqlens_k)[None]
    out = scaled_dot_product_attention(
        query[None], key[None], value[None],
        dropout_p=dropout if training else 0.0, is_causal=causal,
        scale=scale, segment_ids=q_seg, kv_segment_ids=k_seg)
    return out[0], None


def sdp_kernel(enable_math=None, enable_flash=None, enable_mem_efficient=None):
    """ref: flash_attention.py::sdp_kernel — backend-selection context.
    On TPU the pallas flash kernel is governed by FLAGS_use_pallas_kernels;
    this context flips it for the duration."""
    import contextlib

    from ...framework.flags import get_flags, set_flags

    @contextlib.contextmanager
    def ctx():
        prev = get_flags(['FLAGS_use_pallas_kernels'])[
            'FLAGS_use_pallas_kernels']
        if enable_flash is not None:
            set_flags({'FLAGS_use_pallas_kernels': bool(enable_flash)})
        try:
            yield
        finally:
            set_flags({'FLAGS_use_pallas_kernels': prev})

    return ctx()


def calc_reduced_attention_scores(query, key, softmax_lse=None):
    """ref: flash_attention.py::calc_reduced_attention_scores — per-query
    attention mass summed over heads (used by sparse-attention tooling)."""
    d = query.shape[-1]
    s = jnp.einsum('bqhd,bkhd->bhqk', query.astype(jnp.float32),
                   key.astype(jnp.float32)) / jnp.sqrt(
                       jnp.asarray(d, jnp.float32))
    p = jax.nn.softmax(s, axis=-1)
    return p.sum(axis=1).astype(query.dtype)
