"""Pooling (ref: python/paddle/nn/functional/pooling.py) via lax.reduce_window."""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def _tuple(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    p = list(padding)
    if all(isinstance(v, int) for v in p):
        return [(v, v) for v in p]
    return [tuple(v) for v in p]


def _window(x, n, kernel, stride, padding, data_format, init, op, ceil_mode=False):
    nc_first = data_format.startswith('NC')
    if nc_first:
        dims = (1, 1) + _tuple(kernel, n)
        strides = (1, 1) + _tuple(stride, n)
        sp_off = 2
    else:
        dims = (1,) + _tuple(kernel, n) + (1,)
        strides = (1,) + _tuple(stride, n) + (1,)
        sp_off = 1
    pad = _pads(padding, n)
    if isinstance(pad, str):
        full_pad = pad
    else:
        full_pad = [(0, 0)] * sp_off + pad + ([(0, 0)] if not nc_first else [])
        if ceil_mode:
            full_pad = [list(p) for p in full_pad]
            for i in range(n):
                ax = sp_off + i
                size = x.shape[ax] + full_pad[ax][0] + full_pad[ax][1]
                rem = (size - dims[ax]) % strides[ax]
                if rem:
                    full_pad[ax][1] += strides[ax] - rem
            full_pad = [tuple(p) for p in full_pad]
    return lax.reduce_window(x, init, op, dims, strides, full_pad), dims, strides


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False, data_format='NCL'):
    stride = stride or kernel_size
    out, _, _ = _window(x, 1, kernel_size, stride, padding, data_format,
                        -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
                        lax.max, ceil_mode)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, data_format='NCHW'):
    stride = stride or kernel_size
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    out, _, _ = _window(x, 2, kernel_size, stride, padding, data_format, init, lax.max, ceil_mode)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, data_format='NCDHW'):
    stride = stride or kernel_size
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    out, _, _ = _window(x, 3, kernel_size, stride, padding, data_format, init, lax.max, ceil_mode)
    return out


def _avg(x, n, kernel_size, stride, padding, ceil_mode, exclusive, data_format):
    stride = stride or kernel_size
    s, dims, strides = _window(
        x.astype(jnp.float32), n, kernel_size, stride, padding, data_format, 0.0, lax.add, ceil_mode
    )
    import numpy as _np

    nonzero_pad = (
        (isinstance(padding, str) and padding.upper() == 'SAME')
        or (not isinstance(padding, str) and _np.any(_np.asarray(padding) != 0))
    )
    if exclusive and (nonzero_pad or ceil_mode):
        ones = jnp.ones_like(x, dtype=jnp.float32)
        cnt, _, _ = _window(ones, n, kernel_size, stride, padding, data_format, 0.0, lax.add, ceil_mode)
        return (s / cnt).astype(x.dtype)
    import numpy as np

    k = int(np.prod(_tuple(kernel_size, n)))
    return (s / k).astype(x.dtype)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, data_format='NCL'):
    return _avg(x, 1, kernel_size, stride, padding, ceil_mode, exclusive, data_format)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format='NCHW'):
    return _avg(x, 2, kernel_size, stride, padding, ceil_mode, exclusive, data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format='NCDHW'):
    return _avg(x, 3, kernel_size, stride, padding, ceil_mode, exclusive, data_format)


def _adaptive(x, n, output_size, data_format, reducer):
    nc_first = data_format.startswith('NC')
    out_size = _tuple(output_size, n)
    sp_axes = list(range(2, 2 + n)) if nc_first else list(range(1, 1 + n))
    out = x
    for ax, osz in zip(sp_axes, out_size):
        if osz is None:
            continue
        isz = out.shape[ax]
        if isz % osz == 0:
            k = isz // osz
            shape = out.shape[:ax] + (osz, k) + out.shape[ax + 1 :]
            out = reducer(out.reshape(shape), ax + 1)
        else:
            pieces = []
            for i in range(osz):
                lo = (i * isz) // osz
                hi = -(-((i + 1) * isz) // osz)
                sl = [slice(None)] * out.ndim
                sl[ax] = slice(lo, hi)
                pieces.append(reducer(out[tuple(sl)], ax, keepdims=True))
            out = jnp.concatenate(pieces, axis=ax)
    return out


def adaptive_avg_pool1d(x, output_size, data_format='NCL'):
    return _adaptive(x, 1, output_size, data_format, lambda v, a, keepdims=False: jnp.mean(v, axis=a, keepdims=keepdims))


def adaptive_avg_pool2d(x, output_size, data_format='NCHW'):
    return _adaptive(x, 2, output_size, data_format, lambda v, a, keepdims=False: jnp.mean(v, axis=a, keepdims=keepdims))


def adaptive_avg_pool3d(x, output_size, data_format='NCDHW'):
    return _adaptive(x, 3, output_size, data_format, lambda v, a, keepdims=False: jnp.mean(v, axis=a, keepdims=keepdims))


def adaptive_max_pool1d(x, output_size, return_mask=False, data_format='NCL'):
    return _adaptive(x, 1, output_size, data_format, lambda v, a, keepdims=False: jnp.max(v, axis=a, keepdims=keepdims))


def adaptive_max_pool2d(x, output_size, return_mask=False, data_format='NCHW'):
    return _adaptive(x, 2, output_size, data_format, lambda v, a, keepdims=False: jnp.max(v, axis=a, keepdims=keepdims))


def adaptive_max_pool3d(x, output_size, return_mask=False, data_format='NCDHW'):
    return _adaptive(x, 3, output_size, data_format, lambda v, a, keepdims=False: jnp.max(v, axis=a, keepdims=keepdims))


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format='NCHW'):
    p = float(norm_type)
    stride = stride or kernel_size
    s, dims, _ = _window(
        jnp.power(jnp.abs(x.astype(jnp.float32)), p), 2, kernel_size, stride, padding,
        data_format, 0.0, lax.add, ceil_mode,
    )
    return jnp.power(s, 1.0 / p).astype(x.dtype)
