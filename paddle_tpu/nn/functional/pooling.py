"""Pooling (ref: python/paddle/nn/functional/pooling.py) via lax.reduce_window."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _tuple(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    p = list(padding)
    if all(isinstance(v, int) for v in p):
        return [(v, v) for v in p]
    return [tuple(v) for v in p]


def _window(x, n, kernel, stride, padding, data_format, init, op, ceil_mode=False):
    nc_first = data_format.startswith('NC')
    if nc_first:
        dims = (1, 1) + _tuple(kernel, n)
        strides = (1, 1) + _tuple(stride, n)
        sp_off = 2
    else:
        dims = (1,) + _tuple(kernel, n) + (1,)
        strides = (1,) + _tuple(stride, n) + (1,)
        sp_off = 1
    pad = _pads(padding, n)
    if isinstance(pad, str):
        full_pad = pad
    else:
        full_pad = [(0, 0)] * sp_off + pad + ([(0, 0)] if not nc_first else [])
        if ceil_mode:
            full_pad = [list(p) for p in full_pad]
            for i in range(n):
                ax = sp_off + i
                size = x.shape[ax] + full_pad[ax][0] + full_pad[ax][1]
                rem = (size - dims[ax]) % strides[ax]
                if rem:
                    full_pad[ax][1] += strides[ax] - rem
            full_pad = [tuple(p) for p in full_pad]
    return lax.reduce_window(x, init, op, dims, strides, full_pad), dims, strides


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format='NCL'):
    stride = stride or kernel_size
    if return_mask:
        return _max_pool_with_indices(x, 1, kernel_size, stride, padding,
                                      ceil_mode, data_format)
    out, _, _ = _window(x, 1, kernel_size, stride, padding, data_format,
                        -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
                        lax.max, ceil_mode)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format='NCHW'):
    stride = stride or kernel_size
    if return_mask:
        return _max_pool_with_indices(x, 2, kernel_size, stride, padding,
                                      ceil_mode, data_format)
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    out, _, _ = _window(x, 2, kernel_size, stride, padding, data_format, init, lax.max, ceil_mode)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format='NCDHW'):
    stride = stride or kernel_size
    if return_mask:
        return _max_pool_with_indices(x, 3, kernel_size, stride, padding,
                                      ceil_mode, data_format)
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    out, _, _ = _window(x, 3, kernel_size, stride, padding, data_format, init, lax.max, ceil_mode)
    return out


def _avg(x, n, kernel_size, stride, padding, ceil_mode, exclusive, data_format):
    stride = stride or kernel_size
    s, dims, strides = _window(
        x.astype(jnp.float32), n, kernel_size, stride, padding, data_format, 0.0, lax.add, ceil_mode
    )
    import numpy as _np

    nonzero_pad = (
        (isinstance(padding, str) and padding.upper() == 'SAME')
        or (not isinstance(padding, str) and _np.any(_np.asarray(padding) != 0))
    )
    if exclusive and (nonzero_pad or ceil_mode):
        ones = jnp.ones_like(x, dtype=jnp.float32)
        cnt, _, _ = _window(ones, n, kernel_size, stride, padding, data_format, 0.0, lax.add, ceil_mode)
        return (s / cnt).astype(x.dtype)
    import numpy as np

    k = int(np.prod(_tuple(kernel_size, n)))
    return (s / k).astype(x.dtype)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, data_format='NCL'):
    return _avg(x, 1, kernel_size, stride, padding, ceil_mode, exclusive, data_format)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format='NCHW'):
    return _avg(x, 2, kernel_size, stride, padding, ceil_mode, exclusive, data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format='NCDHW'):
    return _avg(x, 3, kernel_size, stride, padding, ceil_mode, exclusive, data_format)


def _adaptive(x, n, output_size, data_format, reducer):
    nc_first = data_format.startswith('NC')
    out_size = _tuple(output_size, n)
    sp_axes = list(range(2, 2 + n)) if nc_first else list(range(1, 1 + n))
    out = x
    for ax, osz in zip(sp_axes, out_size):
        if osz is None:
            continue
        isz = out.shape[ax]
        if isz % osz == 0:
            k = isz // osz
            shape = out.shape[:ax] + (osz, k) + out.shape[ax + 1 :]
            out = reducer(out.reshape(shape), ax + 1)
        else:
            pieces = []
            for i in range(osz):
                lo = (i * isz) // osz
                hi = -(-((i + 1) * isz) // osz)
                sl = [slice(None)] * out.ndim
                sl[ax] = slice(lo, hi)
                pieces.append(reducer(out[tuple(sl)], ax, keepdims=True))
            out = jnp.concatenate(pieces, axis=ax)
    return out


def adaptive_avg_pool1d(x, output_size, data_format='NCL'):
    return _adaptive(x, 1, output_size, data_format, lambda v, a, keepdims=False: jnp.mean(v, axis=a, keepdims=keepdims))


def adaptive_avg_pool2d(x, output_size, data_format='NCHW'):
    return _adaptive(x, 2, output_size, data_format, lambda v, a, keepdims=False: jnp.mean(v, axis=a, keepdims=keepdims))


def adaptive_avg_pool3d(x, output_size, data_format='NCDHW'):
    return _adaptive(x, 3, output_size, data_format, lambda v, a, keepdims=False: jnp.mean(v, axis=a, keepdims=keepdims))


def _adaptive_max(x, n, output_size, return_mask, data_format):
    if not return_mask:
        return _adaptive(x, n, output_size, data_format,
                         lambda v, a, keepdims=False: jnp.max(v, axis=a, keepdims=keepdims))
    # indices path: adaptive regions [floor(i*in/out), ceil((i+1)*in/out))
    import numpy as np
    xc, restore = _to_nc(x, n, data_format)
    spatial = xc.shape[2:]
    out_size = _tuple(output_size, n)
    out_size = tuple(spatial[i] if out_size[i] is None else out_size[i]
                     for i in range(n))
    bounds = []
    for i in range(n):
        idx = np.arange(out_size[i])
        starts = (idx * spatial[i]) // out_size[i]
        ends = -(-((idx + 1) * spatial[i]) // out_size[i])
        bounds.append((starts, ends))
    return _region_max_pool(xc, n, bounds, out_size, True, restore)


def adaptive_max_pool1d(x, output_size, return_mask=False, data_format='NCL'):
    return _adaptive_max(x, 1, output_size, return_mask, data_format)


def adaptive_max_pool2d(x, output_size, return_mask=False, data_format='NCHW'):
    return _adaptive_max(x, 2, output_size, return_mask, data_format)


def adaptive_max_pool3d(x, output_size, return_mask=False, data_format='NCDHW'):
    return _adaptive_max(x, 3, output_size, return_mask, data_format)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format='NCHW'):
    p = float(norm_type)
    stride = stride or kernel_size
    s, dims, _ = _window(
        jnp.power(jnp.abs(x.astype(jnp.float32)), p), 2, kernel_size, stride, padding,
        data_format, 0.0, lax.add, ceil_mode,
    )
    return jnp.power(s, 1.0 / p).astype(x.dtype)


# ---- max-pool indices / unpooling / fractional pooling ----------------------
# (ref: nn/functional/pooling.py::max_pool*(return_mask), max_unpool1d/2d/3d,
# fractional_max_pool2d/3d). Indices are flattened over the UNPADDED spatial
# dims, as the reference kernels produce. The window argmax is computed by
# stacking the prod(kernel) strided slices (static unroll — XLA fuses this
# into one gather-free elementwise reduction) rather than reduce_window,
# which cannot carry an argmax payload.
import itertools as _it

import numpy as _np


def _to_nc(x, n, data_format):
    """Canonicalize to NC-first; returns (x, restore_fn)."""
    if data_format.startswith('NC'):
        return x, lambda v: v
    perm = (0, n + 1) + tuple(range(1, n + 1))
    inv = (0,) + tuple(range(2, n + 2)) + (1,)
    return x.transpose(perm), lambda v: v.transpose(inv)


def _max_pool_with_indices(x, n, kernel, stride, padding, ceil_mode,
                           data_format):
    x, restore = _to_nc(x, n, data_format)
    k, s = _tuple(kernel, n), _tuple(stride, n)
    pad = _pads(padding, n)
    if isinstance(pad, str):
        pad = [(0, 0)] * n if pad == 'VALID' else None
        if pad is None:
            raise ValueError("padding='SAME' unsupported with return_mask")
    spatial = x.shape[2:]
    pad = [list(p) for p in pad]
    out_sizes = []
    for i in range(n):
        size = spatial[i] + pad[i][0] + pad[i][1]
        if ceil_mode:
            rem = (size - k[i]) % s[i]
            if rem:
                pad[i][1] += s[i] - rem
                size += s[i] - rem
        out_sizes.append((size - k[i]) // s[i] + 1)

    # integers compare exactly in their own dtype (a float32 cast would
    # round values above 2^24); floats go through f32 with -inf padding
    if jnp.issubdtype(x.dtype, jnp.floating):
        cmp_dtype, pad_val = jnp.float32, -jnp.inf
    else:
        cmp_dtype, pad_val = x.dtype, jnp.iinfo(x.dtype).min
    xp = jnp.pad(x.astype(cmp_dtype), [(0, 0), (0, 0)] + [tuple(p) for p in pad],
                 constant_values=pad_val)
    idx_map = jnp.arange(int(_np.prod(spatial)), dtype=jnp.int32).reshape(spatial)
    idx_map = jnp.pad(idx_map, [tuple(p) for p in pad], constant_values=-1)

    vals, idxs = [], []
    for offs in _it.product(*[range(kk) for kk in k]):
        sl = tuple(slice(offs[i], offs[i] + (out_sizes[i] - 1) * s[i] + 1, s[i])
                   for i in range(n))
        vals.append(xp[(slice(None), slice(None)) + sl])
        idxs.append(idx_map[sl])
    vals = jnp.stack(vals, axis=-1)             # (N, C, *out, K)
    idxs = jnp.stack(idxs, axis=-1)             # (*out, K)
    best = jnp.argmax(vals, axis=-1)
    out = jnp.take_along_axis(vals, best[..., None], axis=-1)[..., 0]
    indices = jnp.take_along_axis(
        jnp.broadcast_to(idxs, vals.shape), best[..., None], axis=-1)[..., 0]
    return (restore(out.astype(x.dtype)),
            restore(indices.astype(jnp.int32)))


def _max_unpool(x, indices, n, kernel_size, stride=None, padding=0,
                output_size=None, data_format='NCHW'):
    x, restore = _to_nc(x, n, data_format)
    indices, _ = _to_nc(indices, n, data_format)
    k = _tuple(kernel_size, n)
    s = _tuple(stride if stride is not None else kernel_size, n)
    p = _tuple(padding, n)
    if output_size is None:
        out_sp = tuple((x.shape[2 + i] - 1) * s[i] - 2 * p[i] + k[i]
                       for i in range(n))
    else:
        out_sp = tuple(output_size[-n:])
    nb, ch = x.shape[:2]
    flat = int(_np.prod(out_sp))

    def scatter(ind, val):
        return jnp.zeros((flat,), val.dtype).at[ind.ravel()].set(val.ravel())

    out = jax.vmap(jax.vmap(scatter))(
        indices.reshape(nb, ch, -1), x.reshape(nb, ch, -1))
    return restore(out.reshape((nb, ch) + out_sp))


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format='NCL'):
    return _max_unpool(x, indices, 1, kernel_size, stride, padding,
                       output_size, data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format='NCHW'):
    return _max_unpool(x, indices, 2, kernel_size, stride, padding,
                       output_size, data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format='NCDHW'):
    return _max_unpool(x, indices, 3, kernel_size, stride, padding,
                       output_size, data_format)


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format='NCL'):
    p = float(norm_type)
    stride = stride or kernel_size
    s, _, _ = _window(
        jnp.power(jnp.abs(x.astype(jnp.float32)), p), 1, kernel_size, stride,
        padding, data_format, 0.0, lax.add, ceil_mode)
    return jnp.power(s, 1.0 / p).astype(x.dtype)


def _fractional_bounds(in_size, out_size, u, kernel=None):
    """Graham's pseudo-random pooling regions: start_i = ceil(a(i+u)-1),
    end_i = ceil(a(i+1+u)-1) (kernel overrides the window length)."""
    alpha = in_size / out_size
    i = _np.arange(out_size)
    starts = _np.ceil(alpha * (i + u) - 1).astype(int).clip(0, in_size - 1)
    if kernel is not None:
        ends = starts + kernel
    else:
        ends = _np.ceil(alpha * (i + 1 + u) - 1).astype(int)
    ends = ends.clip(1, in_size)
    ends = _np.maximum(ends, starts + 1)
    return starts, ends


def _fractional_max_pool(x, n, output_size, kernel_size, random_u,
                         return_mask, data_format):
    x, restore = _to_nc(x, n, data_format)
    spatial = x.shape[2:]
    out_size = _tuple(output_size, n)
    out_size = tuple(spatial[i] if out_size[i] is None else out_size[i]
                     for i in range(n))
    k = _tuple(kernel_size, n) if kernel_size is not None else (None,) * n
    if random_u is None:
        from ...framework import random as _rand
        random_u = float(jax.random.uniform(_rand.split_key(), ()))
    if not (0 < random_u < 1):
        raise ValueError(f'random_u must be in (0, 1), got {random_u}')

    bounds = [_fractional_bounds(spatial[i], out_size[i], random_u, k[i])
              for i in range(n)]
    return _region_max_pool(x, n, bounds, out_size, return_mask, restore)


def _region_max_pool(x, n, bounds, out_size, return_mask, restore):
    """Max over per-dim variable-length regions given as (starts, ends)
    numpy arrays — shared by fractional and adaptive max pooling."""
    spatial = x.shape[2:]
    maxw = [int((e - s).max()) for s, e in bounds]
    # gather indices (out_i, maxw_i) per dim + validity masks
    gidx, gmask = [], []
    for i in range(n):
        starts, ends = bounds[i]
        offs = _np.arange(maxw[i])
        idx = starts[:, None] + offs[None]
        mask = idx < ends[:, None]
        gidx.append(jnp.asarray(idx.clip(0, spatial[i] - 1)))
        gmask.append(jnp.asarray(mask))
    # patch gather: successively index each spatial dim
    patches = x
    for i in range(n):
        ax = 2 + i * 2  # each expansion splits dim i into (out_i, maxw_i)
        patches = jnp.moveaxis(
            jnp.take(patches, gidx[i].ravel(), axis=ax), ax, ax
        ).reshape(patches.shape[:ax] + (out_size[i], maxw[i])
                  + patches.shape[ax + 1:])
    # patches: (N, C, out_0, w_0, out_1, w_1, ...) -> bring windows last
    perm = ([0, 1] + [2 + 2 * i for i in range(n)]
            + [3 + 2 * i for i in range(n)])
    patches = patches.transpose(perm)
    win = patches.reshape(patches.shape[:2 + n] + (-1,))
    # build combined window mask with broadcasting
    m = gmask[0].reshape(out_size[0], maxw[0], *([1, 1] * (n - 1)))
    for i in range(1, n):
        shape = [1, 1] * n
        shape[2 * i], shape[2 * i + 1] = out_size[i], maxw[i]
        m = m * gmask[i].reshape(shape)
    m = m.transpose([2 * i for i in range(n)] + [2 * i + 1 for i in range(n)])
    m = m.reshape(out_size + (-1,))
    win = jnp.where(m, win.astype(jnp.float32), -jnp.inf)
    out = jnp.max(win, axis=-1).astype(x.dtype)
    if not return_mask:
        return restore(out)
    # global flat index of the argmax within the unpadded input
    best = jnp.argmax(win, axis=-1)
    flat_idx = 0
    for i in range(n):
        # window-local offset along dim i of the flattened window position
        stride_rest = int(_np.prod(maxw[i + 1:])) if i + 1 <= n - 1 else 1
        loc = (best // stride_rest) % maxw[i]
        starts = jnp.asarray(bounds[i][0])
        shape = [1] * n
        shape[i] = out_size[i]
        dim_idx = starts.reshape(shape) + loc
        flat_idx = flat_idx * spatial[i] + dim_idx
    indices = jnp.broadcast_to(flat_idx, out.shape).astype(jnp.int32)
    return restore(out), restore(indices)


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, data_format='NCHW'):
    return _fractional_max_pool(x, 2, output_size, kernel_size, random_u,
                                return_mask, data_format)


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, data_format='NCDHW'):
    return _fractional_max_pool(x, 3, output_size, kernel_size, random_u,
                                return_mask, data_format)
