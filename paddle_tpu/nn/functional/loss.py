"""Loss functionals (ref: python/paddle/nn/functional/loss.py).

Log-space formulations throughout; reductions in fp32 for bf16 inputs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _reduce(x, reduction):
    if reduction == 'mean':
        return jnp.mean(x)
    if reduction == 'sum':
        return jnp.sum(x)
    return x


def cross_entropy(
    input,
    label,
    weight=None,
    ignore_index=-100,
    reduction='mean',
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
):
    """ref: paddle.nn.functional.cross_entropy."""
    logits = input.astype(jnp.float32)
    if use_softmax:
        logp = jax.nn.log_softmax(logits, axis=axis)
    else:
        logp = jnp.log(jnp.clip(logits, 1e-30, None))
    n_classes = input.shape[axis]
    if soft_label or (hasattr(label, 'dtype') and jnp.issubdtype(label.dtype, jnp.floating) and label.ndim == input.ndim):
        tgt = label.astype(jnp.float32)
        if label_smoothing > 0:
            tgt = tgt * (1 - label_smoothing) + label_smoothing / n_classes
        loss = -jnp.sum(tgt * logp, axis=axis)
        mask = None
    else:
        lbl = label
        if lbl.ndim == input.ndim:
            lbl = jnp.squeeze(lbl, axis=axis)
        mask = lbl != ignore_index
        safe = jnp.where(mask, lbl, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe, axis).astype(jnp.int32), axis=axis
        )
        picked = jnp.squeeze(picked, axis=axis)
        if label_smoothing > 0:
            mean_logp = jnp.mean(logp, axis=axis)
            picked = (1 - label_smoothing) * picked + label_smoothing * mean_logp
        loss = -jnp.where(mask, picked, 0.0)
        if weight is not None:
            w = jnp.take(weight.astype(jnp.float32), safe)
            loss = loss * jnp.where(mask, w, 0.0)
            if reduction == 'mean':
                return jnp.sum(loss) / jnp.clip(jnp.sum(jnp.where(mask, w, 0.0)), 1e-12, None)
        if reduction == 'mean':
            return jnp.sum(loss) / jnp.clip(jnp.sum(mask.astype(jnp.float32)), 1.0, None)
    return _reduce(loss, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, axis=-1, return_softmax=False):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index, reduction='none', axis=axis)
    loss = jnp.expand_dims(loss, axis)
    if return_softmax:
        return loss, jax.nn.softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction='mean'):
    """input is log-probabilities (ref: F.nll_loss)."""
    return _nll(input, label, weight, ignore_index, reduction)


def _nll(logp, label, weight, ignore_index, reduction):
    mask = label != ignore_index
    safe = jnp.where(mask, label, 0)
    picked = jnp.take_along_axis(logp, safe[..., None].astype(jnp.int32), axis=-1)[..., 0]
    loss = -jnp.where(mask, picked, 0.0)
    if weight is not None:
        w = jnp.take(weight, safe)
        loss = loss * w
        if reduction == 'mean':
            return jnp.sum(loss) / jnp.sum(jnp.where(mask, w, 0.0))
    if reduction == 'mean':
        return jnp.sum(loss) / jnp.clip(jnp.sum(mask), 1, None)
    return _reduce(loss, reduction)


def mse_loss(input, label, reduction='mean'):
    return _reduce(jnp.square(input.astype(jnp.float32) - label.astype(jnp.float32)), reduction)


def l1_loss(input, label, reduction='mean'):
    return _reduce(jnp.abs(input - label), reduction)


def smooth_l1_loss(input, label, reduction='mean', delta=1.0):
    d = jnp.abs(input - label)
    return _reduce(jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta)), reduction)


def huber_loss(input, label, delta=1.0, reduction='mean'):
    d = jnp.abs(input - label)
    return _reduce(jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta)), reduction)


def binary_cross_entropy(input, label, weight=None, reduction='mean'):
    x = jnp.clip(input.astype(jnp.float32), 1e-12, 1 - 1e-12)
    loss = -(label * jnp.log(x) + (1 - label) * jnp.log1p(-x))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction='mean', pos_weight=None):
    z = logit.astype(jnp.float32)
    y = label.astype(jnp.float32)
    base = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
    if pos_weight is not None:
        log_w = (pos_weight - 1) * y + 1
        base = jnp.maximum(z, 0) * (1 - y) + log_w * (
            jnp.log1p(jnp.exp(-jnp.abs(z))) + jnp.maximum(-z, 0)
        ) - 0  # stable pos-weighted form
        base = (1 - y) * z + log_w * (jnp.log1p(jnp.exp(-jnp.abs(z))) + jnp.maximum(-z, 0))
    if weight is not None:
        base = base * weight
    return _reduce(base, reduction)


def kl_div(input, label, reduction='mean', log_target=False):
    if log_target:
        loss = jnp.exp(label) * (label - input)
    else:
        loss = label * (jnp.log(jnp.clip(label, 1e-30, None)) - input)
    if reduction == 'batchmean':
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction='mean'):
    return _reduce(jnp.maximum(0, -label * (input - other) + margin), reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction='mean'):
    loss = jnp.where(label == 1, input, jnp.maximum(0, margin - input))
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction='mean'):
    from .common import cosine_similarity

    cos = cosine_similarity(input1, input2, axis=-1)
    loss = jnp.where(label == 1, 1 - cos, jnp.maximum(0, cos - margin))
    return _reduce(loss, reduction)


def triplet_margin_loss(anchor, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction='mean'):
    def dist(a, b):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p), axis=-1), 1 / p)

    dp = dist(anchor, positive)
    dn = dist(anchor, negative)
    if swap:
        dn = jnp.minimum(dn, dist(positive, negative))
    return _reduce(jnp.maximum(dp - dn + margin, 0), reduction)


def multi_label_soft_margin_loss(input, label, weight=None, reduction='mean'):
    loss = -(label * jax.nn.log_sigmoid(input) + (1 - label) * jax.nn.log_sigmoid(-input))
    if weight is not None:
        loss = loss * weight
    return _reduce(jnp.mean(loss, axis=-1), reduction)


def soft_margin_loss(input, label, reduction='mean'):
    return _reduce(jnp.log1p(jnp.exp(-label * input)), reduction)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8, reduction='mean'):
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        stirling = label * jnp.log(label + epsilon) - label + 0.5 * jnp.log(2 * jnp.pi * (label + epsilon))
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    return _reduce(loss, reduction)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6, reduction='mean'):
    var = jnp.clip(variance, epsilon, None)
    loss = 0.5 * (jnp.log(var) + jnp.square(input - label) / var)
    if full:
        loss = loss + 0.5 * jnp.log(2 * jnp.asarray(jnp.pi))
    return _reduce(loss, reduction)


def square_error_cost(input, label):
    return jnp.square(input - label)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction='mean', norm_by_times=False):
    """CTC via the standard dynamic program in log space (lax.scan over time).

    ref: nn/functional/loss.py::ctc_loss ("aliased as softmax with CTC"):
    `log_probs` is the UNSCALED logit sequence, shape (T, B, C) — softmax
    is applied internally, matching warp-ctc. `norm_by_times` scales the
    gradient (not the value) by 1/T_i per sequence, as warp-ctc does.
    """
    T, B, C = log_probs.shape
    L = labels.shape[1]
    S = 2 * L + 1
    ninf = jnp.float32(-1e30)
    lp = jax.nn.log_softmax(log_probs.astype(jnp.float32), axis=-1)

    ext = jnp.full((B, S), blank, dtype=labels.dtype)
    ext = ext.at[:, 1::2].set(labels)

    same_as_prevprev = jnp.concatenate(
        [jnp.zeros((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1
    )

    alpha0 = jnp.full((B, S), ninf)
    alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
    alpha0 = alpha0.at[:, 1].set(jnp.take_along_axis(lp[0], ext[:, 1:2].astype(jnp.int32), axis=1)[:, 0])

    def lse(*xs):
        stacked = jnp.stack(xs)
        m = jnp.max(stacked, axis=0)
        m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
        return jnp.where(
            jnp.isfinite(m),
            m_safe + jnp.log(jnp.sum(jnp.exp(stacked - m_safe), axis=0)),
            ninf,
        )

    def step(alpha, lp_t):
        prev1 = jnp.concatenate([jnp.full((B, 1), ninf), alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate([jnp.full((B, 2), ninf), alpha[:, :-2]], axis=1)
        prev2 = jnp.where(same_as_prevprev, ninf, prev2)
        emit = jnp.take_along_axis(lp_t, ext.astype(jnp.int32), axis=1)
        new = lse(alpha, prev1, prev2) + emit
        return new, new

    _, alphas = jax.lax.scan(step, alpha0, lp[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # (T, B, S)

    t_idx = jnp.clip(input_lengths - 1, 0, T - 1)
    last = alphas[t_idx, jnp.arange(B)]  # (B, S)
    s_last = 2 * label_lengths  # blank after last label
    a1 = jnp.take_along_axis(last, s_last[:, None].astype(jnp.int32), axis=1)[:, 0]
    a2 = jnp.take_along_axis(
        last, jnp.clip(s_last - 1, 0, S - 1)[:, None].astype(jnp.int32), axis=1
    )[:, 0]
    m = jnp.maximum(a1, a2)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    ll = m_safe + jnp.log(jnp.exp(a1 - m_safe) + jnp.exp(a2 - m_safe))
    loss = -ll
    if norm_by_times:
        # warp-ctc semantics: divide the GRADIENT by the sequence length,
        # leaving the loss value unchanged (the reference forwards the
        # flag to warpctc for every reduction mode)
        t = jnp.clip(input_lengths.astype(jnp.float32), 1, None)
        loss = loss / t + jax.lax.stop_gradient(loss - loss / t)
    if reduction == 'mean':
        return jnp.mean(loss / jnp.clip(label_lengths.astype(jnp.float32), 1, None))
    return _reduce(loss, reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction='sum'):
    p = jax.nn.sigmoid(logit)
    ce = binary_cross_entropy_with_logits(logit, label, reduction='none')
    p_t = p * label + (1 - p) * (1 - label)
    loss = ce * jnp.power(1 - p_t, gamma)
    if alpha >= 0:
        a_t = alpha * label + (1 - alpha) * (1 - label)
        loss = a_t * loss
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


def dice_loss(input, label, epsilon=1e-5):
    label_oh = jax.nn.one_hot(jnp.squeeze(label, -1), input.shape[-1], dtype=input.dtype)
    reduce_axes = tuple(range(1, input.ndim))
    inter = 2 * jnp.sum(input * label_oh, axis=reduce_axes)
    denom = jnp.sum(input, axis=reduce_axes) + jnp.sum(label_oh, axis=reduce_axes)
    return jnp.mean(1 - (inter + epsilon) / (denom + epsilon))


def log_loss(input, label, epsilon=1e-4):
    return -label * jnp.log(input + epsilon) - (1 - label) * jnp.log(1 - input + epsilon)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    sim = anchor @ positive.T
    labels = labels.reshape(-1)
    tgt = (labels[:, None] == labels[None, :]).astype(jnp.float32)
    tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
    ce = jnp.mean(-jnp.sum(tgt * jax.nn.log_softmax(sim, axis=1), axis=1))
    reg = l2_reg * (jnp.mean(jnp.sum(anchor * anchor, axis=1)) + jnp.mean(jnp.sum(positive * positive, axis=1))) * 0.25
    return ce + reg


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction='mean'):
    """Multi-class margin (hinge) loss (ref: loss.py::multi_margin_loss):
    mean_j( max(0, margin - x[y] + x[j])^p ) over j != y."""
    x = input.astype(jnp.float32)
    n, c = x.shape
    xy = jnp.take_along_axis(x, label[:, None], axis=1)
    m = jnp.maximum(0.0, margin - xy + x)
    if p != 1:
        m = m ** p
    if weight is not None:
        m = m * jnp.take(weight.astype(jnp.float32), label)[:, None]
    # the j == y term contributes max(0, margin)^p; mask it out
    m = m * (1 - jax.nn.one_hot(label, c, dtype=m.dtype))
    return _reduce(jnp.sum(m, axis=1) / c, reduction)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction='mean'):
    """ref: loss.py::triplet_margin_with_distance_loss — like
    triplet_margin_loss but with a caller-supplied distance."""
    if distance_function is None:
        distance_function = lambda a, b: jnp.linalg.norm(a - b, axis=-1)
    dp = distance_function(input, positive)
    dn = distance_function(input, negative)
    if swap:
        dn = jnp.minimum(dn, distance_function(positive, negative))
    return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False):
    """Hierarchical sigmoid loss (ref: loss.py::hsigmoid_loss; bit layout
    per phi/kernels/funcs/matrix_bit_code.h::SimpleCode — class c is heap
    node c + num_classes; weight row for prefix node n is n - 1).

    Default tree: complete binary heap over the classes. Custom tree:
    `path_table` [N, L] rows of weight indices (negative = padding) and
    `path_code` [N, L] binary targets.
    """
    x = input.astype(jnp.float32)
    label = label.reshape(-1)
    if path_table is None:
        # static max code length: bits of (2*num_classes - 1) minus 1
        max_len = int(2 * num_classes - 1).bit_length() - 1
        c = label + num_classes
        bits = jnp.arange(max_len)
        # integer floor(log2(c)): count of powers of two <= c (float log2
        # rounds the wrong way exactly at the powers of two)
        length = jnp.sum(
            c[:, None] >= (1 << jnp.arange(1, max_len + 1))[None],
            axis=1).astype(jnp.int32)
        valid = bits[None, :] < length[:, None]
        # bit i (LSB-first): weight index (c >> (i+1)) - 1, target (c >> i) & 1
        idx = jnp.where(valid, (c[:, None] >> (bits[None] + 1)) - 1, 0)
        code = ((c[:, None] >> bits[None]) & 1).astype(jnp.float32)
    else:
        valid = path_table >= 0
        idx = jnp.where(valid, path_table, 0)
        code = path_code.astype(jnp.float32)
    w = jnp.take(weight.astype(jnp.float32), idx, axis=0)   # (N, L, D)
    pre = jnp.einsum('nd,nld->nl', x, w)
    if bias is not None:
        pre = pre + jnp.take(bias.astype(jnp.float32).reshape(-1), idx)
    # BCE-with-logits vs target bit, summed over the path
    per_node = jax.nn.softplus(pre) - code * pre
    return jnp.sum(jnp.where(valid, per_node, 0.0), axis=1, keepdims=True)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction='mean'):
    """ArcFace-family margin softmax (ref: loss.py::margin_cross_entropy):
    target logit cos(theta) -> cos(m1*theta + m2) - m3, all scaled by s.

    `group`: None/False computes locally; a mesh axis NAME (str) computes
    the class-parallel version under shard_map — the TPU analogue of the
    reference's model-parallel process group. `logits` is then the LOCAL
    class shard (equal widths across the axis); labels are GLOBAL class
    ids, translated to shard-local columns via the shard's axis index so
    only the owning shard applies the margin / contributes the NLL term.
    """
    x = logits.astype(jnp.float32)
    label = label.reshape(-1)
    n, c = x.shape
    if isinstance(group, str):  # class-parallel: x is the local shard
        offset = jax.lax.axis_index(group) * c
        local = label - offset
        in_shard = (local >= 0) & (local < c)
        onehot = (jax.nn.one_hot(jnp.clip(local, 0, c - 1), c, dtype=x.dtype)
                  * in_shard[:, None].astype(x.dtype))
        # owner shard contributes the target cosine; everyone gets it
        cos_t = jax.lax.psum(jnp.sum(x * onehot, axis=-1), group)
    else:
        onehot = jax.nn.one_hot(label, c, dtype=x.dtype)
        cos_t = jnp.sum(x * onehot, axis=-1)
    cos_t = jnp.clip(cos_t, -1.0, 1.0)
    theta = jnp.arccos(cos_t)
    target = jnp.cos(margin1 * theta + margin2) - margin3
    adjusted = x * (1 - onehot) + target[:, None] * onehot
    z = adjusted * scale
    if isinstance(group, str):
        zmax = jax.lax.pmax(jnp.max(z, axis=-1), group)
        e = jnp.exp(z - zmax[:, None])
        denom = jax.lax.psum(jnp.sum(e, axis=-1), group)
        logp = z - zmax[:, None] - jnp.log(denom)[:, None]
        softmax = e / denom[:, None]
        # onehot is zero off the owner shard, so psum counts the term once
        nll = jax.lax.psum(-jnp.sum(logp * onehot, axis=-1), group)
    else:
        logp = jax.nn.log_softmax(z, axis=-1)
        softmax = jnp.exp(logp)
        nll = -jnp.sum(logp * onehot, axis=-1)
    loss = _reduce(nll[:, None], reduction)
    return (loss, softmax) if return_softmax else loss


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None):
    """Adaptive softmax (Grave et al.) — frequent classes in the head,
    rare classes in down-projected tail clusters
    (ref: loss.py::adaptive_log_softmax_with_loss). `tail_weights[i]` is
    [proj (D, d_i), out (d_i, n_i)]; `cutoffs` ends with n_classes.

    Returns (output, loss): per-sample target log-prob and mean NLL.
    """
    x = input.astype(jnp.float32)
    cutoffs = [int(v) for v in cutoffs]
    shortlist = cutoffs[0]
    n_clusters = len(cutoffs) - 1
    head = x @ head_weight
    if head_bias is not None:
        head = head + head_bias
    head_logp = jax.nn.log_softmax(head, axis=-1)   # (N, shortlist + K)

    out = jnp.take_along_axis(
        head_logp, jnp.clip(label, 0, shortlist - 1)[:, None], axis=1)[:, 0]
    out = jnp.where(label < shortlist, out, 0.0)
    for i in range(n_clusters):
        lo, hi = cutoffs[i], cutoffs[i + 1]
        proj, w_out = tail_weights[i]
        tail_logp = jax.nn.log_softmax((x @ proj) @ w_out, axis=-1)
        in_cluster = (label >= lo) & (label < hi)
        rel = jnp.clip(label - lo, 0, hi - lo - 1)
        lp = (head_logp[:, shortlist + i]
              + jnp.take_along_axis(tail_logp, rel[:, None], axis=1)[:, 0])
        out = jnp.where(in_cluster, lp, out)
    return out, -jnp.mean(out)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction='mean'):
    """RNN-Transducer loss (ref: loss.py::rnnt_loss; the reference wraps
    warp-transducer's CUDA kernel).

    TPU-native design: the forward-variable recurrence runs as an outer
    `lax.scan` over T with an inner scan over U (static [B, Tmax, Umax]
    grid, length masking instead of dynamic shapes). Gradients come from
    autodiff through the scan rather than a hand-written backward.
    FastEmit regularization scales the emission branch's *gradient* by
    (1 + lambda) without changing the loss value — implemented with a
    stop_gradient identity, exactly matching warp-transducer's behaviour.
    """
    lp = jax.nn.log_softmax(input.astype(jnp.float32), axis=-1)
    b, tmax, umax_p1, _ = lp.shape
    umax = umax_p1 - 1
    neg_inf = jnp.float32(-1e30)

    blank_lp = lp[..., blank]                            # (B, T, U+1)
    lab = jnp.clip(label, 0, None).astype(jnp.int32)     # (B, Umax)
    emit_lp = jnp.take_along_axis(
        lp[:, :, :umax, :], lab[:, None, :, None], axis=-1)[..., 0]
    if fastemit_lambda:
        emit_lp = emit_lp + fastemit_lambda * (
            emit_lp - jax.lax.stop_gradient(emit_lp))
    u_range = jnp.arange(umax_p1)
    u_valid = u_range[None] <= label_lengths[:, None]    # (B, U+1)

    alpha0 = jnp.where(u_range[None] == 0, 0.0, neg_inf)
    alpha0 = jnp.broadcast_to(alpha0, (b, umax_p1))

    def t_step(alpha_prev, t):
        # blank transition from the previous time step
        from_blank = alpha_prev + blank_lp[:, t - 1, :]

        def u_step(carry, u):
            # emit transition within this time step: alpha[t, u] also
            # hears alpha[t, u-1] + emit_lp[t, u-1]
            prev_u = carry
            here = from_blank[:, u]
            emit = jnp.where(u > 0,
                             prev_u + emit_lp[:, t, jnp.maximum(u - 1, 0)],
                             neg_inf)
            val = jnp.logaddexp(here, emit)
            return val, val

        _, cols = jax.lax.scan(u_step, jnp.full((b,), neg_inf), u_range)
        alpha_t = cols.T                                  # (B, U+1)
        # first time step keeps only the emit chain from alpha[0, 0]
        alpha_t = jnp.where(u_valid, alpha_t, neg_inf)
        return alpha_t, alpha_t

    # t = 0 row: pure emission chain alpha[0, u] = sum emit_lp[0, :u]
    emit0 = jnp.concatenate(
        [jnp.zeros((b, 1)), jnp.cumsum(emit_lp[:, 0, :], axis=-1)], axis=-1)
    alpha_t0 = jnp.where(u_valid, emit0, neg_inf)

    if tmax > 1:
        _, rows = jax.lax.scan(
            lambda a, t: t_step(a, t), alpha_t0, jnp.arange(1, tmax))
        alphas = jnp.concatenate([alpha_t0[None], rows], axis=0)  # (T, B, U+1)
    else:
        alphas = alpha_t0[None]
    # final log-prob: alpha[T_b - 1, U_b] + blank at (T_b - 1, U_b)
    t_idx = (input_lengths - 1).astype(jnp.int32)
    u_idx = label_lengths.astype(jnp.int32)
    batch = jnp.arange(b)
    final_alpha = alphas[t_idx, batch, u_idx]
    final_blank = blank_lp[batch, t_idx, u_idx]
    nll = -(final_alpha + final_blank)
    return _reduce(nll, reduction)
