"""Gradient clipping (ref: python/paddle/nn/clip.py).

Clip objects transform a *gradient pytree* functionally — attached to an
optimizer via ``grad_clip=`` exactly like Paddle.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


class ClipGradBase:
    def __call__(self, grads):  # pragma: no cover - abstract
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, grads):
        return jax.tree.map(lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipGradByNorm(ClipGradBase):
    """Per-tensor norm clip."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        def clip(g):
            n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(n, 1e-12))
            return (g * scale).astype(g.dtype)

        return jax.tree.map(clip, grads)


class ClipGradByGlobalNorm(ClipGradBase):
    """Global-norm clip (the Fleet default for LLM training)."""

    def __init__(self, clip_norm, group_name='default_group'):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        leaves = jax.tree.leaves(grads)
        gn = jnp.sqrt(
            sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
        )
        scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(gn, 1e-12))
        return jax.tree.map(lambda g: (g * scale).astype(g.dtype), grads)


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    """Functional global-norm clip over a grad pytree; returns (grads, norm)."""
    leaves = jax.tree.leaves(parameters)
    if norm_type == float('inf'):
        gn = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in leaves]))
    else:
        gn = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(g.astype(jnp.float32)), norm_type)) for g in leaves),
            1.0 / norm_type,
        )
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(gn, 1e-12))
    return jax.tree.map(lambda g: (g * scale).astype(g.dtype), parameters), gn


def clip_grad_value_(parameters, clip_value):
    return jax.tree.map(lambda g: jnp.clip(g, -clip_value, clip_value), parameters)
