"""paddle_tpu.nn.quant (ref: python/paddle/nn/quant/__init__.py).

The inference-time quantized-matmul surface over the pallas weight-only
kernels (`ops/pallas/quant_matmul.py`): int8 and fp8 weights with
per-output-channel scales, dequantized in VMEM right before the MXU.
"""
from __future__ import annotations

import jax.numpy as jnp


def weight_quantize(x, algo='weight_only_int8', arch=None, group_size=-1):
    """ref: paddle.nn.quant.weight_quantize — (quantized weight, scale).
    algos: weight_only_int8, weight_only_int4 (PACKED: two 4-bit codes
    per int8 byte along K, output shape (⌈K/2⌉, N)), llm.int8, fp8
    variants via the e4m3 path."""
    from ...ops.pallas.quant_matmul import (quantize_weight,
                                            quantize_weight_fp8,
                                            quantize_weight_int4)

    if algo in ('weight_only_int8', 'llm.int8'):
        return quantize_weight(x)
    if algo == 'weight_only_int4':
        # PACKED like the reference: two 4-bit codes per int8 byte along
        # K (rows ⌈K/2⌉) — half the int8 path's HBM traffic; the pallas
        # kernel sign-extends both nibbles in VMEM
        return quantize_weight_int4(x)
    if algo in ('fp8', 'weight_only_fp8', 'float8_e4m3fn'):
        return quantize_weight_fp8(x)
    raise ValueError(f'unknown quantize algo: {algo}')


def weight_dequantize(x, scale, algo='weight_only_int8', out_dtype='float32',
                      out_features=None):
    """ref: paddle.nn.quant.weight_dequantize.

    For packed int4, ``out_features`` recovers an odd original K (the
    packer adds one zero pad row; without it the padded row is kept)."""
    if algo == 'weight_only_int4':
        from ...ops.pallas.quant_matmul import _unpack_int4

        codes = _unpack_int4(x)
        if out_features is not None:
            codes = codes[:out_features]
        return (codes * scale).astype(out_dtype)
    return (x.astype(jnp.float32) * scale).astype(out_dtype)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype='int8', arch=None, group_size=-1):
    """ref: paddle.nn.quant.weight_only_linear — the pallas fast path."""
    from ...ops.pallas.quant_matmul import weight_only_linear as wol

    return wol(x, weight, weight_scale, bias=bias,
               weight_dtype=weight_dtype)


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """ref: paddle.nn.quant.llm_int8_linear — LLM.int8's outlier
    decomposition exists to protect fp16 accumulation on CUDA; the MXU
    accumulates int8 matmuls in fp32, so the plain weight-only kernel is
    already outlier-safe and IS the implementation."""
    from ...ops.pallas.quant_matmul import weight_only_linear as wol

    return wol(x, weight, weight_scale, bias=bias)


class Stub:
    """ref: paddle.nn.quant.Stub — placeholder layer replaced by an
    observer/quanter when QAT prepares the model."""

    def __init__(self, observer=None):
        self._observer = observer

    def forward(self, x):
        return x if self._observer is None else self._observer(x)

    __call__ = forward
