"""paddle_tpu.nn.quant (ref: python/paddle/nn/quant/__init__.py).

The inference-time quantized-matmul surface over the pallas weight-only
kernels (`ops/pallas/quant_matmul.py`): int8 and fp8 weights with
per-output-channel scales, dequantized in VMEM right before the MXU.
"""
from __future__ import annotations

import jax.numpy as jnp


def weight_quantize(x, algo='weight_only_int8', arch=None, group_size=-1):
    """ref: paddle.nn.quant.weight_quantize — (quantized weight, scale).
    algos: weight_only_int8, weight_only_int4 (stored as int8 range
    [-8, 7]), llm.int8, fp8 variants via the e4m3 path."""
    from ...ops.pallas.quant_matmul import quantize_weight, quantize_weight_fp8

    if algo in ('weight_only_int8', 'llm.int8'):
        return quantize_weight(x)
    if algo == 'weight_only_int4':
        # quantize directly onto the int4 grid (int8 storage, like the
        # reference): scale = absmax/7 so codes span [-7, 7]
        absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=0)
        scale = jnp.where(absmax == 0, 1.0, absmax / 7.0)
        wq = jnp.clip(jnp.round(x / scale), -8, 7).astype(jnp.int8)
        return wq, scale
    if algo in ('fp8', 'weight_only_fp8', 'float8_e4m3fn'):
        return quantize_weight_fp8(x)
    raise ValueError(f'unknown quantize algo: {algo}')


def weight_dequantize(x, scale, algo='weight_only_int8', out_dtype='float32'):
    """ref: paddle.nn.quant.weight_dequantize."""
    return (x.astype(jnp.float32) * scale).astype(out_dtype)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype='int8', arch=None, group_size=-1):
    """ref: paddle.nn.quant.weight_only_linear — the pallas fast path."""
    from ...ops.pallas.quant_matmul import weight_only_linear as wol

    return wol(x, weight, weight_scale, bias=bias)


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """ref: paddle.nn.quant.llm_int8_linear — LLM.int8's outlier
    decomposition exists to protect fp16 accumulation on CUDA; the MXU
    accumulates int8 matmuls in fp32, so the plain weight-only kernel is
    already outlier-safe and IS the implementation."""
    from ...ops.pallas.quant_matmul import weight_only_linear as wol

    return wol(x, weight, weight_scale, bias=bias)


class Stub:
    """ref: paddle.nn.quant.Stub — placeholder layer replaced by an
    observer/quanter when QAT prepares the model."""

    def __init__(self, observer=None):
        self._observer = observer

    def forward(self, x):
        return x if self._observer is None else self._observer(x)

    __call__ = forward
