"""paddle_tpu.nn.quant (ref: python/paddle/nn/quant/__init__.py).

The inference-time quantized-matmul surface over the pallas weight-only
kernels (`ops/pallas/quant_matmul.py`): int8 and fp8 weights with
per-output-channel scales, dequantized in VMEM right before the MXU.
"""
from __future__ import annotations

import jax as _jax
import jax.numpy as jnp


def weight_quantize(x, algo='weight_only_int8', arch=None, group_size=-1):
    """ref: paddle.nn.quant.weight_quantize — (quantized weight, scale).
    algos: weight_only_int8, weight_only_int4 (PACKED: two 4-bit codes
    per int8 byte along K, output shape (⌈K/2⌉, N)), llm.int8, fp8
    variants via the e4m3 path."""
    from ...ops.pallas.quant_matmul import (quantize_weight,
                                            quantize_weight_fp8,
                                            quantize_weight_int4)

    if algo in ('weight_only_int8', 'llm.int8'):
        return quantize_weight(x)
    if algo == 'weight_only_int4':
        # PACKED like the reference: two 4-bit codes per int8 byte along
        # K (rows ⌈K/2⌉) — half the int8 path's HBM traffic; the pallas
        # kernel sign-extends both nibbles in VMEM
        return quantize_weight_int4(x)
    if algo in ('fp8', 'weight_only_fp8', 'float8_e4m3fn'):
        return quantize_weight_fp8(x)
    raise ValueError(f'unknown quantize algo: {algo}')


def weight_dequantize(x, scale, algo='weight_only_int8', out_dtype='float32',
                      out_features=None):
    """ref: paddle.nn.quant.weight_dequantize.

    For packed int4, ``out_features`` recovers an odd original K (the
    packer adds one zero pad row; without it the padded row is kept)."""
    if algo == 'weight_only_int4':
        from ...ops.pallas.quant_matmul import _unpack_int4

        codes = _unpack_int4(x)
        if out_features is not None:
            codes = codes[:out_features]
        return (codes * scale).astype(out_dtype)
    return (x.astype(jnp.float32) * scale).astype(out_dtype)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype='int8', arch=None, group_size=-1):
    """ref: paddle.nn.quant.weight_only_linear — the pallas fast path."""
    from ...ops.pallas.quant_matmul import weight_only_linear as wol

    return wol(x, weight, weight_scale, bias=bias,
               weight_dtype=weight_dtype)


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """ref: paddle.nn.quant.llm_int8_linear — LLM.int8's outlier
    decomposition exists to protect fp16 accumulation on CUDA; the MXU
    accumulates int8 matmuls in fp32, so the plain weight-only kernel is
    already outlier-safe and IS the implementation."""
    from ...ops.pallas.quant_matmul import weight_only_linear as wol

    return wol(x, weight, weight_scale, bias=bias)


@_jax.tree_util.register_pytree_node_class
class QuantizedWeight:
    """A weight-only quantized matrix: codes + per-output-column scale.

    Drop-in replacement for a dense (K, N) projection Parameter inside a
    Layer pytree (meta-registered attributes stay children whatever their
    type), used by `LlamaForCausalLM.quantize_weights` and friends.
    `codes`/`scale` are the pytree leaves; `bits` rides in the treedef.
    `matmul(x)` routes through the pallas weight-only kernel.
    """

    def __init__(self, codes, scale, bits=8, shape=None):
        self.codes = codes
        self.scale = scale
        self.bits = int(bits)
        # logical (K, N) of the dense weight this replaces (int4 packs
        # two codes per byte, so codes.shape underreports K)
        self._shape = tuple(shape) if shape is not None else tuple(
            getattr(codes, 'shape', ()))

    @classmethod
    def quantize(cls, w, bits=8):
        algo = {8: 'weight_only_int8', 4: 'weight_only_int4'}.get(bits)
        if algo is None:
            raise ValueError(f'bits must be 4 or 8, got {bits}')
        codes, scale = weight_quantize(w, algo=algo)
        return cls(codes, scale, bits, shape=w.shape)

    def matmul(self, x):
        return weight_only_linear(
            x, self.codes, weight_scale=self.scale,
            weight_dtype='int4' if self.bits == 4 else 'int8')

    def __rmatmul__(self, x):
        # jax arrays/tracers return NotImplemented for unrecognized
        # matmul operands, so plain `x @ w` model code works unchanged
        # when w has been swapped for a QuantizedWeight
        return self.matmul(x)

    # -- array-ish protocol: Layer repr/astype/state_dict iterate params
    # and expect shape/dtype; codes' integer dtype makes floating-only
    # casts (amp O2, Layer.astype) skip this weight, which is the right
    # semantic — the codes are fixed-point by construction.
    @property
    def shape(self):
        return self._shape

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dtype(self):
        return self.codes.dtype

    def astype(self, dtype):
        """Quantized codes have a fixed dtype; only the scale casts."""
        return type(self)(self.codes, self.scale.astype(dtype), self.bits,
                          self._shape)

    def _state_dict_entries(self):
        """Split into plain-array entries so checkpoints round-trip
        (Layer.state_dict expands these as `<name>.codes`/`<name>.scale`
        and `_set_by_path` writes them back onto this object)."""
        return [('codes', self.codes), ('scale', self.scale)]

    def tree_flatten(self):
        return (self.codes, self.scale), (self.bits, self._shape)

    @classmethod
    def tree_unflatten(cls, aux, children):
        bits, shape = aux
        return cls(children[0], children[1], bits, shape)

    def __repr__(self):
        return (f'QuantizedWeight(bits={self.bits}, shape={self._shape}, '
                f'codes={getattr(self.codes, "shape", None)})')


@_jax.tree_util.register_pytree_node_class
class QuantizedExpertWeight:
    """Weight-only int8 for BATCHED expert weights (E, K, N) — the MoE
    counterpart of QuantizedWeight (ref capability: the reference's
    weight-only pass over fused-MoE expert projections). codes int8 with
    per-(expert, out-column) scales; the expert einsums consume it via
    `einsum()`, which feeds the int8 codes straight into the dot (the
    HBM-resident weight stays 1 byte/element — the serving win) and
    applies the scale on the output. The ragged (dropless) path
    dequantizes before `lax.ragged_dot` (documented cost: that path's
    HBM saving depends on XLA fusing the convert)."""

    def __init__(self, codes, scale, shape=None):
        self.codes = codes
        self.scale = scale
        self.bits = 8
        self._shape = tuple(shape) if shape is not None else tuple(
            getattr(codes, 'shape', ()))

    @classmethod
    def quantize(cls, w, bits=8):
        if bits != 8:
            raise ValueError(
                'expert weights support int8 only (int4 packing along '
                'the per-expert K axis is not implemented)')
        amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=1)  # (E, N)
        scale = jnp.maximum(amax, 1e-8) / 127.0
        codes = jnp.clip(jnp.round(w.astype(jnp.float32)
                                     / scale[:, None, :]),
                          -127, 127).astype(jnp.int8)
        return cls(codes, scale, shape=w.shape)

    def einsum(self, eq, x):
        """jnp.einsum(eq, x, w) with the scale applied on the output
        axis (the out axis is always last in the expert equations).
        The dot runs at x's dtype (bf16 keeps MXU throughput; the codes
        convert tile-wise inside the fused dot) with fp32 accumulation;
        only the small output picks up the fp32 scale."""
        out = jnp.einsum(eq, x, self.codes.astype(x.dtype),
                         preferred_element_type=jnp.float32)
        return (out * self.scale[:, None, :]).astype(x.dtype)

    def dequantize(self, dtype=jnp.float32):
        return (self.codes.astype(jnp.float32)
                * self.scale[:, None, :]).astype(dtype)

    @property
    def shape(self):
        return self._shape

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dtype(self):
        return self.codes.dtype

    def astype(self, dtype):
        return type(self)(self.codes, self.scale.astype(dtype), self._shape)

    def _state_dict_entries(self):
        return [('codes', self.codes), ('scale', self.scale)]

    def tree_flatten(self):
        return (self.codes, self.scale), (self._shape,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0])

    def __repr__(self):
        return (f'QuantizedExpertWeight(shape={self._shape}, '
                f'codes={getattr(self.codes, "shape", None)})')


class Stub:
    """ref: paddle.nn.quant.Stub — placeholder layer replaced by an
    observer/quanter when QAT prepares the model."""

    def __init__(self, observer=None):
        self._observer = observer

    def forward(self, x):
        return x if self._observer is None else self._observer(x)

    __call__ = forward
