"""paddle_tpu.nn (ref: python/paddle/nn/__init__.py)."""
from . import decode  # noqa: F401
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import quant  # noqa: F401
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
from .clip import (  # noqa: F401
    ClipGradByGlobalNorm,
    ClipGradByNorm,
    ClipGradByValue,
)
from .layer.activation import *  # noqa: F401,F403
from .layer.base import Buffer, Layer, Parameter  # noqa: F401
from .layer.common import (  # noqa: F401
    AlphaDropout,
    Bilinear,
    ChannelShuffle,
    CosineSimilarity,
    FeatureAlphaDropout,
    Unflatten,
    ZeroPad1D,
    ZeroPad3D,
    Dropout,
    Dropout2D,
    Dropout3D,
    Flatten,
    Fold,
    Identity,
    Linear,
    Embedding,
    Pad1D,
    Pad2D,
    Pad3D,
    PairwiseDistance,
    PixelShuffle,
    PixelUnshuffle,
    Unfold,
    Upsample,
    UpsamplingBilinear2D,
    UpsamplingNearest2D,
    ZeroPad2D,
)
from .layer.container import LayerDict, LayerList, ParameterList, Sequential  # noqa: F401
from .layer.conv import (  # noqa: F401
    Conv1D,
    Conv1DTranspose,
    Conv2D,
    Conv2DTranspose,
    Conv3D,
    Conv3DTranspose,
)
from .layer.loss import *  # noqa: F401,F403
from .layer.norm import (  # noqa: F401
    BatchNorm,
    BatchNorm1D,
    BatchNorm2D,
    BatchNorm3D,
    GroupNorm,
    InstanceNorm1D,
    InstanceNorm2D,
    InstanceNorm3D,
    LayerNorm,
    LocalResponseNorm,
    RMSNorm,
    SpectralNorm,
    SyncBatchNorm,
)
from .layer.pooling import *  # noqa: F401,F403
from .layer.rnn import (  # noqa: F401
    GRU,
    LSTM,
    BiRNN,
    GRUCell,
    LSTMCell,
    RNN,
    RNNCellBase,
    SimpleRNN,
    SimpleRNNCell,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention,
    Transformer,
    TransformerDecoder,
    TransformerDecoderLayer,
    TransformerEncoder,
    TransformerEncoderLayer,
)
from . import utils  # noqa: F401
