"""Pooling layers (ref: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from .. import functional as F
from .base import Layer


class _Pool(Layer):
    def __init__(self, kernel_size=None, stride=None, padding=0, ceil_mode=False,
                 data_format=None, output_size=None, **kw):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format
        self.output_size = output_size


class MaxPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format='NCL', name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format)
        self.return_mask = return_mask

    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                              self.return_mask, self.ceil_mode, self.data_format)


class MaxPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format='NCHW', name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format)
        self.return_mask = return_mask

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                              self.return_mask, self.ceil_mode, self.data_format)


class MaxPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format='NCDHW', name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format)
        self.return_mask = return_mask

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                              self.return_mask, self.ceil_mode, self.data_format)


class AvgPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, data_format='NCL', name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format)
        self.exclusive = exclusive

    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding, self.exclusive, self.ceil_mode, self.data_format)


class AvgPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format='NCHW', name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format)
        self.exclusive = exclusive

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding, self.ceil_mode, self.exclusive, None, self.data_format)


class AvgPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format='NCDHW', name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format)
        self.exclusive = exclusive

    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding, self.ceil_mode, self.exclusive, None, self.data_format)


class AdaptiveAvgPool1D(_Pool):
    def __init__(self, output_size, name=None):
        super().__init__(output_size=output_size, data_format='NCL')

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size, self.data_format)


class AdaptiveAvgPool2D(_Pool):
    def __init__(self, output_size, data_format='NCHW', name=None):
        super().__init__(output_size=output_size, data_format=data_format)

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class AdaptiveAvgPool3D(_Pool):
    def __init__(self, output_size, data_format='NCDHW', name=None):
        super().__init__(output_size=output_size, data_format=data_format)

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, self.data_format)


class AdaptiveMaxPool1D(_Pool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size=output_size, data_format='NCL')
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, self.return_mask,
                                       data_format=self.data_format)


class AdaptiveMaxPool2D(_Pool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size=output_size, data_format='NCHW')
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size, self.return_mask,
                                       data_format=self.data_format)


class AdaptiveMaxPool3D(_Pool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size=output_size, data_format='NCDHW')
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.return_mask,
                                       data_format=self.data_format)


class MaxUnPool1D(Layer):
    """ref: nn/layer/pooling.py::MaxUnPool1D."""

    def __init__(self, kernel_size, stride=None, padding=0, data_format='NCL',
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format, self.output_size = data_format, output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.output_size, self.data_format)


class MaxUnPool2D(Layer):
    """ref: nn/layer/pooling.py::MaxUnPool2D."""

    def __init__(self, kernel_size, stride=None, padding=0, data_format='NCHW',
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format, self.output_size = data_format, output_size

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.output_size, self.data_format)


class MaxUnPool3D(Layer):
    """ref: nn/layer/pooling.py::MaxUnPool3D."""

    def __init__(self, kernel_size, stride=None, padding=0, data_format='NCDHW',
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format, self.output_size = data_format, output_size

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.output_size, self.data_format)


class LPPool1D(Layer):
    """ref: nn/layer/pooling.py::LPPool1D."""

    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format='NCL', name=None):
        super().__init__()
        self.norm_type, self.kernel_size = norm_type, kernel_size
        self.stride, self.padding = stride, padding
        self.ceil_mode, self.data_format = ceil_mode, data_format

    def forward(self, x):
        return F.lp_pool1d(x, self.norm_type, self.kernel_size, self.stride,
                           self.padding, self.ceil_mode, self.data_format)


class LPPool2D(Layer):
    """ref: nn/layer/pooling.py::LPPool2D."""

    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format='NCHW', name=None):
        super().__init__()
        self.norm_type, self.kernel_size = norm_type, kernel_size
        self.stride, self.padding = stride, padding
        self.ceil_mode, self.data_format = ceil_mode, data_format

    def forward(self, x):
        return F.lp_pool2d(x, self.norm_type, self.kernel_size, self.stride,
                           self.padding, self.ceil_mode, self.data_format)


class FractionalMaxPool2D(Layer):
    """ref: nn/layer/pooling.py::FractionalMaxPool2D."""

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size, self.kernel_size = output_size, kernel_size
        self.random_u, self.return_mask = random_u, return_mask

    def forward(self, x):
        return F.fractional_max_pool2d(x, self.output_size, self.kernel_size,
                                       self.random_u, self.return_mask)


class FractionalMaxPool3D(Layer):
    """ref: nn/layer/pooling.py::FractionalMaxPool3D."""

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size, self.kernel_size = output_size, kernel_size
        self.random_u, self.return_mask = random_u, return_mask

    def forward(self, x):
        return F.fractional_max_pool3d(x, self.output_size, self.kernel_size,
                                       self.random_u, self.return_mask)
