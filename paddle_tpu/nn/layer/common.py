"""Common layers (ref: python/paddle/nn/layer/common.py)."""
from __future__ import annotations

import jax.numpy as jnp

from .. import functional as F
from .. import initializer as I
from .base import Layer, Parameter


class Linear(Layer):
    """ref: paddle.nn.Linear — weight stored (in_features, out_features)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), initializer=_init_of(weight_attr)
        )
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (out_features,), is_bias=True, initializer=_init_of(bias_attr, bias=True)
            )
        else:
            self.bias = None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)


def _init_of(attr, bias=False):
    if attr is None or attr is True:
        return None
    if isinstance(attr, I.Initializer):
        return attr
    if hasattr(attr, 'initializer'):  # ParamAttr-like
        return attr.initializer
    return None


class Embedding(Layer):
    """ref: paddle.nn.Embedding."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        init = _init_of(weight_attr) or I.Normal(0.0, 1.0)
        self.weight = Parameter(init((num_embeddings, embedding_dim), jnp.float32))
        if padding_idx is not None:
            self.weight = Parameter(self.weight.value.at[padding_idx].set(0.0))

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode='upscale_in_train', name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode
        self._init_rng()

    def forward(self, x):
        if not self.training or self.p == 0:
            return F.dropout(x, self.p, self.axis, False, self.mode)
        return F.dropout(x, self.p, self.axis, True, self.mode, rng_key=self.next_rng_key())


class Dropout2D(Dropout):
    def __init__(self, p=0.5, data_format='NCHW', name=None):
        super().__init__(p=p, axis=None)
        self.data_format = data_format

    def forward(self, x):
        if not self.training or self.p == 0:
            return x
        return F.dropout2d(x, self.p, True, self.data_format, rng_key=self.next_rng_key())


class Dropout3D(Dropout):
    def __init__(self, p=0.5, data_format='NCDHW', name=None):
        super().__init__(p=p, axis=None)
        self.data_format = data_format

    def forward(self, x):
        if not self.training or self.p == 0:
            return x
        return F.dropout3d(x, self.p, True, self.data_format, rng_key=self.next_rng_key())


class AlphaDropout(Dropout):
    def forward(self, x):
        if not self.training or self.p == 0:
            return x
        return F.alpha_dropout(x, self.p, True, rng_key=self.next_rng_key())


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ...tensor.manipulation import flatten

        return flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter((out_features, in1_features, in2_features))
        self.bias = None if bias_attr is False else self.create_parameter((1, out_features), is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode='nearest', align_corners=False, data_format='NCHW', name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners, self.data_format = mode, align_corners, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode, self.align_corners, self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format='NCHW', name=None):
        super().__init__(size, scale_factor, 'bilinear', True, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format='NCHW', name=None):
        super().__init__(size, scale_factor, 'nearest', False, data_format)


class _PadNd(Layer):
    def __init__(self, padding, mode='constant', value=0.0, data_format='NCHW'):
        super().__init__()
        self.padding = list(padding) if not isinstance(padding, int) else None
        self._int_pad = padding if isinstance(padding, int) else None
        self.mode, self.value, self.data_format = mode, value, data_format
        self._n = {'NCL': 1, 'NLC': 1, 'NCHW': 2, 'NHWC': 2, 'NCDHW': 3, 'NDHWC': 3}[data_format]

    def forward(self, x):
        from ...tensor.manipulation import pad as pad_fn

        p = self.padding if self.padding is not None else [self._int_pad] * (2 * self._n)
        if self.data_format.startswith('NC'):
            return pad_fn(x, p, self.mode, self.value)
        # channels-last: pad spatial dims (1..n)
        pairs = [(0, 0)] * x.ndim
        it = list(zip(p[0::2], p[1::2]))
        for i, pr in enumerate(reversed(it)):
            pairs[1 + i] = pr
        if self.mode == 'constant':
            return jnp.pad(x, pairs, constant_values=self.value)
        jmode = {'reflect': 'reflect', 'replicate': 'edge', 'circular': 'wrap'}[self.mode]
        return jnp.pad(x, pairs, mode=jmode)


class Pad1D(_PadNd):
    def __init__(self, padding, mode='constant', value=0.0, data_format='NCL', name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    def __init__(self, padding, mode='constant', value=0.0, data_format='NCHW', name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode='constant', value=0.0, data_format='NCDHW', name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    pass


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format='NCHW', name=None):
        super().__init__()
        self.upscale_factor, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format='NCHW', name=None):
        super().__init__()
        self.downscale_factor, self.data_format = downscale_factor, data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    """Rearrange channels across groups (ref: nn/layer/vision.py::ChannelShuffle)."""

    def __init__(self, groups, data_format='NCHW', name=None):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.kernel_sizes, self.strides = kernel_sizes, strides
        self.paddings, self.dilations = paddings, dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings, self.dilations)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.output_sizes, self.kernel_sizes = output_sizes, kernel_sizes
        self.strides, self.paddings, self.dilations = strides, paddings, dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes, self.strides, self.paddings, self.dilations)


class ZeroPad1D(Pad1D):
    """ref: nn/layer/common.py::ZeroPad1D(padding, data_format, name)."""

    def __init__(self, padding, data_format='NCL', name=None):
        super().__init__(padding, 'constant', 0.0, data_format)


class ZeroPad3D(Pad3D):
    """ref: nn/layer/common.py::ZeroPad3D(padding, data_format, name)."""

    def __init__(self, padding, data_format='NCDHW', name=None):
        super().__init__(padding, 'constant', 0.0, data_format)


class FeatureAlphaDropout(Layer):
    """ref: nn/layer/common.py::FeatureAlphaDropout."""

    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, self.p, self.training)


class Unflatten(Layer):
    """Expand one axis into the given shape
    (ref: nn/layer/common.py::Unflatten)."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, tuple(shape)

    def forward(self, x):
        from ...tensor.extension import unflatten

        return unflatten(x, self.axis, self.shape)
