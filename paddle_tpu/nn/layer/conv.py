"""Conv layers (ref: python/paddle/nn/layer/conv.py).

Weights in Paddle layout (out, in/groups, *k); compute via
lax.conv_general_dilated (MXU path). `data_format` passthrough supports
channels-last for TPU-optimal layouts.
"""
from __future__ import annotations

import numpy as np

from .. import functional as F
from .. import initializer as I
from .base import Layer
from .common import _init_of


def _ntuple(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _ConvNd(Layer):
    def __init__(
        self,
        in_channels,
        out_channels,
        kernel_size,
        n,
        stride=1,
        padding=0,
        dilation=1,
        groups=1,
        padding_mode='zeros',
        weight_attr=None,
        bias_attr=None,
        data_format=None,
        transpose=False,
        output_padding=0,
    ):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self._n = n
        self.kernel_size = _ntuple(kernel_size, n)
        self.stride = _ntuple(stride, n)
        self.padding = padding
        self.output_padding = output_padding
        self.dilation = _ntuple(dilation, n)
        self.groups = groups
        self.padding_mode = padding_mode
        self.data_format = data_format
        self._transpose = transpose
        if transpose:
            w_shape = (in_channels, out_channels // groups) + self.kernel_size
        else:
            w_shape = (out_channels, in_channels // groups) + self.kernel_size
        fan_in = in_channels // groups * int(np.prod(self.kernel_size))
        init = _init_of(weight_attr) or I.KaimingUniform(fan_in=fan_in, negative_slope=np.sqrt(5))
        self.weight = self.create_parameter(w_shape, initializer=init)
        if bias_attr is not False:
            bound = 1 / np.sqrt(fan_in)
            b_init = _init_of(bias_attr, bias=True) or I.Uniform(-bound, bound)
            self.bias = self.create_parameter((out_channels,), initializer=b_init, is_bias=True)
        else:
            self.bias = None


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode='zeros', weight_attr=None,
                 bias_attr=None, data_format='NCL'):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode='zeros', weight_attr=None,
                 bias_attr=None, data_format='NCHW'):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode='zeros', weight_attr=None,
                 bias_attr=None, data_format='NCDHW'):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format='NCL'):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, 'zeros', weight_attr, bias_attr, data_format,
                         transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride, self.padding,
                                  self.output_padding, self.groups, self.dilation, self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format='NCHW'):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, 'zeros', weight_attr, bias_attr, data_format,
                         transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride, self.padding,
                                  self.output_padding, self.groups, self.dilation, self.data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format='NCDHW'):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, 'zeros', weight_attr, bias_attr, data_format,
                         transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride, self.padding,
                                  self.output_padding, self.groups, self.dilation, self.data_format)
