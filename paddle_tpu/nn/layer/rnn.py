"""Recurrent layers (ref: python/paddle/nn/layer/rnn.py).

Recurrences run under `lax.scan` — the XLA-native loop: compiled once,
unrolled on-device, differentiable, static shapes. Gate matmuls are
batched so each scan step is one MXU-friendly (B, 4H) matmul.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .. import functional as F
from .. import initializer as I
from .base import Layer
from .container import LayerList


class RNNCellBase(Layer):
    def get_initial_states(self, batch_size, dtype=jnp.float32):
        shape = (batch_size, self.hidden_size)
        if self._state_arity == 2:
            return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
        return jnp.zeros(shape, dtype)


def _uniform_std(hidden_size):
    std = 1.0 / math.sqrt(hidden_size)
    return I.Uniform(-std, std)


class SimpleRNNCell(RNNCellBase):
    _state_arity = 1

    def __init__(self, input_size, hidden_size, activation='tanh',
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        init = _uniform_std(hidden_size)
        self.weight_ih = self.create_parameter((input_size, hidden_size), initializer=init)
        self.weight_hh = self.create_parameter((hidden_size, hidden_size), initializer=init)
        self.bias_ih = self.create_parameter((hidden_size,), initializer=init, is_bias=True)
        self.bias_hh = self.create_parameter((hidden_size,), initializer=init, is_bias=True)

    def forward(self, inputs, states=None):
        h = states if states is not None else self.get_initial_states(inputs.shape[0], inputs.dtype)
        z = inputs @ self.weight_ih + self.bias_ih + h @ self.weight_hh + self.bias_hh
        act = jnp.tanh if self.activation == 'tanh' else F.relu
        h = act(z)
        return h, h


class LSTMCell(RNNCellBase):
    _state_arity = 2

    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, proj_size=0):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        init = _uniform_std(hidden_size)
        self.weight_ih = self.create_parameter((input_size, 4 * hidden_size), initializer=init)
        self.weight_hh = self.create_parameter((hidden_size, 4 * hidden_size), initializer=init)
        self.bias_ih = self.create_parameter((4 * hidden_size,), initializer=init, is_bias=True)
        self.bias_hh = self.create_parameter((4 * hidden_size,), initializer=init, is_bias=True)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs.shape[0], inputs.dtype)
        h, c = states
        z = inputs @ self.weight_ih + self.bias_ih + h @ self.weight_hh + self.bias_hh
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return h, (h, c)


class GRUCell(RNNCellBase):
    _state_arity = 1

    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        init = _uniform_std(hidden_size)
        self.weight_ih = self.create_parameter((input_size, 3 * hidden_size), initializer=init)
        self.weight_hh = self.create_parameter((hidden_size, 3 * hidden_size), initializer=init)
        self.bias_ih = self.create_parameter((3 * hidden_size,), initializer=init, is_bias=True)
        self.bias_hh = self.create_parameter((3 * hidden_size,), initializer=init, is_bias=True)

    def forward(self, inputs, states=None):
        h = states if states is not None else self.get_initial_states(inputs.shape[0], inputs.dtype)
        zi = inputs @ self.weight_ih + self.bias_ih
        zh = h @ self.weight_hh + self.bias_hh
        ri, ui, ci = jnp.split(zi, 3, axis=-1)
        rh, uh, ch = jnp.split(zh, 3, axis=-1)
        r = jax.nn.sigmoid(ri + rh)
        u = jax.nn.sigmoid(ui + uh)
        c = jnp.tanh(ci + r * ch)
        h = u * h + (1 - u) * c
        return h, h


class RNN(Layer):
    """Runs a cell over time with lax.scan (ref: paddle.nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if not self.time_major:
            inputs = jnp.swapaxes(inputs, 0, 1)  # (T, B, C)
        if self.is_reverse:
            inputs = jnp.flip(inputs, axis=0)
        if initial_states is None:
            initial_states = self.cell.get_initial_states(inputs.shape[1], inputs.dtype)

        cell = self.cell

        def step(state, x_t):
            out, new_state = cell(x_t, state)
            return new_state, out

        final, outs = jax.lax.scan(step, initial_states, inputs)
        if self.is_reverse:
            outs = jnp.flip(outs, axis=0)
        if not self.time_major:
            outs = jnp.swapaxes(outs, 0, 1)
        return outs, final


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        s_fw, s_bw = initial_states if initial_states is not None else (None, None)
        out_fw, f_fw = self.rnn_fw(inputs, s_fw)
        out_bw, f_bw = self.rnn_bw(inputs, s_bw)
        return jnp.concatenate([out_fw, out_bw], axis=-1), (f_fw, f_bw)


class _StackedRNN(Layer):
    """Shared driver for SimpleRNN / LSTM / GRU (ref: nn/layer/rnn.py::RNNBase)."""

    def __init__(self, cell_cls, input_size, hidden_size, num_layers=1,
                 direction='forward', time_major=False, dropout=0.0, **cell_kwargs):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ('bidirect', 'bidirectional')
        self._state_arity = cell_cls._state_arity
        self.layers = LayerList()
        for i in range(num_layers):
            isz = input_size if i == 0 else hidden_size * (2 if self.bidirect else 1)
            if self.bidirect:
                self.layers.append(
                    BiRNN(cell_cls(isz, hidden_size, **cell_kwargs),
                          cell_cls(isz, hidden_size, **cell_kwargs), time_major)
                )
            else:
                self.layers.append(RNN(cell_cls(isz, hidden_size, **cell_kwargs), False, time_major))
        if dropout > 0:
            self._init_rng()

    def forward(self, inputs, initial_states=None, sequence_length=None):
        out = inputs
        finals = []
        for i, rnn in enumerate(self.layers):
            state_i = None if initial_states is None else jax.tree.map(
                lambda s: s[i], initial_states
            )
            out, final = rnn(out, state_i)
            finals.append(final)
            if self.dropout > 0 and self.training and i < self.num_layers - 1:
                out = F.dropout(out, self.dropout, training=True, rng_key=self.next_rng_key())
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *finals)
        return out, stacked


class SimpleRNN(_StackedRNN):
    def __init__(self, input_size, hidden_size, num_layers=1, direction='forward',
                 time_major=False, dropout=0.0, activation='tanh', **kw):
        super().__init__(SimpleRNNCell, input_size, hidden_size, num_layers,
                         direction, time_major, dropout, activation=activation)


class LSTM(_StackedRNN):
    def __init__(self, input_size, hidden_size, num_layers=1, direction='forward',
                 time_major=False, dropout=0.0, **kw):
        super().__init__(LSTMCell, input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class GRU(_StackedRNN):
    def __init__(self, input_size, hidden_size, num_layers=1, direction='forward',
                 time_major=False, dropout=0.0, **kw):
        super().__init__(GRUCell, input_size, hidden_size, num_layers,
                         direction, time_major, dropout)
