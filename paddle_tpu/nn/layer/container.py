"""Layer containers (ref: python/paddle/nn/layer/container.py).

Children are stored as numbered/named attributes so they participate in
pytree flattening like any other sub-layer.
"""
from __future__ import annotations

from .base import Layer, Parameter


class Sequential(Layer):
    """ref: paddle.nn.Sequential."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and not isinstance(
            layers[0], Layer
        ):
            layers = tuple(layers[0])
        named = []
        for i, l in enumerate(layers):
            if isinstance(l, tuple):
                name, l = l
            else:
                name = str(i)
            named.append(name)
            self.add_sublayer(f"L{name}", l)
        self._names = tuple(named)

    def __len__(self):
        return len(self._names)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            items = [getattr(self, f"L{n}") for n in self._names[idx]]
            return Sequential(*items)
        return getattr(self, f"L{self._names[idx]}")

    def __iter__(self):
        for n in self._names:
            yield getattr(self, f"L{n}")

    def forward(self, x):
        for n in self._names:
            x = getattr(self, f"L{n}")(x)
        return x


class LayerList(Layer):
    """ref: paddle.nn.LayerList."""

    def __init__(self, sublayers=None):
        super().__init__()
        self._n = 0
        for l in sublayers or []:
            self.append(l)

    def append(self, layer):
        self.add_sublayer(f"L{self._n}", layer)
        self._n += 1
        return self

    def __len__(self):
        return self._n

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return [getattr(self, f"L{i}") for i in range(self._n)][idx]
        if idx < 0:
            idx += self._n
        return getattr(self, f"L{idx}")

    def __setitem__(self, idx, layer):
        if idx < 0:
            idx += self._n
        self.add_sublayer(f"L{idx}", layer)

    def __iter__(self):
        for i in range(self._n):
            yield getattr(self, f"L{i}")


class ParameterList(Layer):
    """ref: paddle.nn.ParameterList."""

    def __init__(self, parameters=None):
        super().__init__()
        self._n = 0
        for p in parameters or []:
            self.append(p)

    def append(self, parameter):
        if not isinstance(parameter, Parameter):
            parameter = Parameter(parameter)
        setattr(self, f"P{self._n}", parameter)
        self._n += 1
        return self

    def __len__(self):
        return self._n

    def __getitem__(self, idx):
        if idx < 0:
            idx += self._n
        return getattr(self, f"P{idx}")

    def __iter__(self):
        for i in range(self._n):
            yield getattr(self, f"P{i}")


class LayerDict(Layer):
    """ref: paddle.nn.LayerDict."""

    def __init__(self, sublayers=None):
        super().__init__()
        self._keys = ()
        for k, v in (sublayers or {}).items():
            self[k] = v

    def __setitem__(self, key, layer):
        if key not in self._keys:
            self._keys = self._keys + (key,)
        self.add_sublayer(f"D{key}", layer)

    def __getitem__(self, key):
        return getattr(self, f"D{key}")

    def __contains__(self, key):
        return key in self._keys

    def __len__(self):
        return len(self._keys)

    def keys(self):
        return self._keys

    def values(self):
        return [self[k] for k in self._keys]

    def items(self):
        return [(k, self[k]) for k in self._keys]
