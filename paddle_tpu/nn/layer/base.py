"""Pytree-native module system.

The TPU-first replacement for Paddle's `nn.Layer` (ref:
python/paddle/nn/layer/layers.py). Paddle layers are mutable Python
objects driven by a C++ dygraph tracer; here a Layer *is a jax pytree*:
array-valued attributes (parameters, buffers, sub-layers) are dynamic
leaves, everything else is static structure. That makes a whole model a
legal argument/return of `jax.jit`, `jax.grad`, `pjit`, `shard_map` —
no tracer, no ProgramDesc; XLA sees one functional program.

Imperative feel is preserved: layers may mutate their own attributes
during forward (BatchNorm running stats, RNG key threading). Under a
traced step the mutations land on the traced copy, and returning the
model from the step function carries them out — the idiomatic jax
"state in, state out" pattern with Paddle's surface syntax.
"""
from __future__ import annotations

import typing
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax.tree_util import GetAttrKey, register_pytree_with_keys

from ...framework import dtype as dtype_mod
from ...framework import random as random_mod

_ARRAY_TYPES = (jax.Array, np.ndarray)


class Parameter:
    """A marker carrying an array plus parameter metadata.

    Assigning a Parameter to a Layer attribute registers it: the array is
    stored directly on the layer (so forward code uses it as a plain
    ``jax.Array``) and the metadata (trainable flag, sharding
    PartitionSpec) is recorded in the layer's ``_param_meta`` table.
    ref: Paddle's EagerParamBase (python/paddle/base/framework.py).
    """

    __slots__ = ('value', 'trainable', 'spec')

    def __init__(self, value, trainable: bool = True, spec=None):
        self.value = jnp.asarray(value) if value is not None else None
        self.trainable = trainable
        self.spec = spec

    def __repr__(self):
        return f"Parameter(shape={getattr(self.value, 'shape', None)}, trainable={self.trainable}, spec={self.spec})"


class Buffer:
    """Marker for non-parameter state (running stats, RNG keys).

    ``persistable=False`` buffers are excluded from ``state_dict``.
    ref: Layer.register_buffer (python/paddle/nn/layer/layers.py).
    """

    __slots__ = ('value', 'persistable')

    def __init__(self, value, persistable: bool = True):
        self.value = value if value is None else jnp.asarray(value)
        self.persistable = persistable


class _Meta(typing.NamedTuple):
    kind: str          # 'param' | 'buffer'
    trainable: bool
    persistable: bool
    spec: typing.Any   # PartitionSpec or None


def _hashable(v):
    """Best-effort conversion of a static attribute to a hashable value."""
    if isinstance(v, list):
        return ('__list__', tuple(_hashable(x) for x in v))
    if isinstance(v, tuple):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return ('__dict__', tuple(sorted((k, _hashable(x)) for k, x in v.items())))
    if isinstance(v, set):
        return ('__set__', frozenset(_hashable(x) for x in v))
    try:
        hash(v)
        return v
    except TypeError:
        return _ByEq(v)


def _unhashable(v):
    if isinstance(v, tuple):
        if len(v) == 2 and v[0] == '__list__':
            return [_unhashable(x) for x in v[1]]
        if len(v) == 2 and v[0] == '__dict__':
            return {k: _unhashable(x) for k, x in v[1]}
        if len(v) == 2 and v[0] == '__set__':
            return {_unhashable(x) for x in v[1]}
        return tuple(_unhashable(x) for x in v)
    if isinstance(v, _ByEq):
        return v.obj
    return v


class _ByEq:
    """Wraps an unhashable static value; compares by equality."""

    __slots__ = ('obj',)

    def __init__(self, obj):
        self.obj = obj

    def __eq__(self, other):
        return isinstance(other, _ByEq) and self.obj == other.obj

    def __hash__(self):
        return 0


# Attributes handled specially by flatten (never children, never plain static).
# _param_grads (a model-shaped cotangent tree deposited by the eager tape)
# and _dygraph (the taping flag) are host-side training-loop state, not
# part of the model pytree.
_INTERNAL = ('_param_meta', '_param_grads', '_dygraph')


def _is_child(v):
    return isinstance(v, _ARRAY_TYPES + (Layer,))


def _flatten_layer(layer: 'Layer'):
    # meta-registered attrs are ALWAYS children, even when None — so a
    # filtered copy (split_trainable) keeps the same treedef as the model.
    meta_names = layer._param_meta
    children, keys, static = [], [], []
    for name in sorted(layer.__dict__):
        if name in _INTERNAL:
            continue
        v = layer.__dict__[name]
        if _is_child(v) or name in meta_names:
            keys.append(name)
            children.append(v)
        else:
            static.append((name, _hashable(v)))
    meta = tuple(sorted(layer._param_meta.items()))
    aux = (type(layer), tuple(keys), tuple(static), meta)
    return children, aux


def _flatten_layer_with_keys(layer: 'Layer'):
    children, aux = _flatten_layer(layer)
    keys = aux[1]
    return [(GetAttrKey(k), c) for k, c in zip(keys, children)], aux


def _unflatten_layer(aux, children):
    cls, keys, static, meta = aux
    obj = object.__new__(cls)
    d = obj.__dict__
    for name, v in static:
        d[name] = _unhashable(v)
    for name, c in zip(keys, children):
        d[name] = c
    d['_param_meta'] = dict(meta)
    return obj


_registered: set = set()


def _register(cls):
    if cls in _registered:
        return
    _registered.add(cls)
    register_pytree_with_keys(
        cls,
        _flatten_layer_with_keys,
        lambda aux, children: _unflatten_layer(aux, children),
        _flatten_layer,
    )


class ParamList(list):
    """`Layer.parameters()` result: a plain list that also remembers the
    owning module (`.owner`), so optimizers constructed with
    `parameters=net.parameters()` can bind dygraph step()/clear_grad()."""

    owner = None


def _args_may_tape(args, kwargs):
    """Cheap pre-filter for the dygraph tape: any Variable visible at the
    call surface (top level or one container deep)?"""
    from ...autograd.eager import Variable

    def scan(v):
        if isinstance(v, Variable):
            return True
        if isinstance(v, (list, tuple)):
            return any(isinstance(x, Variable) for x in v)
        if isinstance(v, dict):
            return any(isinstance(x, Variable) for x in v.values())
        return False

    return any(scan(a) for a in args) or any(scan(v) for v in kwargs.values())


class Layer:
    """Base class for all network modules (ref: paddle.nn.Layer)."""

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        _register(cls)

    def __init__(self, name_scope=None, dtype=None):
        d = self.__dict__
        d.setdefault('_param_meta', {})
        d.setdefault('training', True)
        d.setdefault('_dtype', dtype_mod.convert_dtype(dtype) if dtype else None)

    # -- attribute registration ------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Parameter):
            self._ensure_init()
            self._param_meta[name] = _Meta('param', value.trainable, True, value.spec)
            object.__setattr__(self, name, value.value)
        elif isinstance(value, Buffer):
            self._ensure_init()
            self._param_meta[name] = _Meta('buffer', False, value.persistable, None)
            object.__setattr__(self, name, value.value)
        else:
            if isinstance(value, _ARRAY_TYPES):
                self._ensure_init()
                # plain array assignment: register as buffer on first set
                if name not in self._param_meta:
                    self._param_meta[name] = _Meta('buffer', False, True, None)
            object.__setattr__(self, name, value)

    def __delattr__(self, name):
        self._param_meta.pop(name, None)
        object.__delattr__(self, name)

    def _ensure_init(self):
        if '_param_meta' not in self.__dict__:
            object.__setattr__(self, '_param_meta', {})
        if 'training' not in self.__dict__:
            object.__setattr__(self, 'training', True)

    # -- parameter creation ----------------------------------------------
    def create_parameter(
        self,
        shape,
        dtype=None,
        initializer=None,
        is_bias: bool = False,
        trainable: bool = True,
        spec=None,
    ) -> Parameter:
        """Create (but not register) a Parameter; assign it to an attribute
        to register. ref: Layer.create_parameter (nn/layer/layers.py)."""
        from .. import initializer as I

        dtype = dtype_mod.convert_dtype(dtype) or dtype_mod.get_default_dtype()
        if initializer is None:
            glob = I.get_global_initializer()
            if glob is not None:
                initializer = glob[1] if is_bias else glob[0]
        if initializer is None:
            initializer = I.Constant(0.0) if is_bias else I.XavierNormal()
        value = initializer(shape, dtype)
        return Parameter(value, trainable=trainable, spec=spec)

    def register_buffer(self, name, value, persistable=True):
        setattr(self, name, Buffer(value, persistable=persistable))

    def add_parameter(self, name, parameter: Parameter):
        setattr(self, name, parameter)
        return getattr(self, name)

    def add_sublayer(self, name, sublayer: 'Layer'):
        setattr(self, name, sublayer)
        return sublayer

    # -- traversal --------------------------------------------------------
    def _children(self):
        meta_names = self._param_meta
        for name in sorted(self.__dict__):
            if name in _INTERNAL:
                continue
            v = self.__dict__[name]
            if _is_child(v) or name in meta_names:
                yield name, v

    def named_sublayers(self, prefix='', include_self=False):
        if include_self:
            yield prefix, self
        for name, v in self._children():
            if isinstance(v, Layer):
                sub_prefix = f"{prefix}.{name}" if prefix else name
                yield from v.named_sublayers(prefix=sub_prefix, include_self=True)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_parameters(self, prefix=''):
        for name, v in self._children():
            path = f"{prefix}.{name}" if prefix else name
            if isinstance(v, Layer):
                yield from v.named_parameters(prefix=path)
            elif self._param_meta.get(name, _META_BUFFER).kind == 'param':
                yield path, v

    def parameters(self):
        # ParamList remembers the owning module: passing it to an
        # optimizer (`Adam(parameters=net.parameters())`) is the dygraph
        # signal that binds opt.step()/clear_grad() to this Layer
        out = ParamList(p for _, p in self.named_parameters())
        out.owner = self
        return out

    def named_buffers(self, prefix='', persistable_only=False):
        for name, v in self._children():
            path = f"{prefix}.{name}" if prefix else name
            if isinstance(v, Layer):
                yield from v.named_buffers(prefix=path, persistable_only=persistable_only)
            else:
                m = self._param_meta.get(name, _META_BUFFER)
                if m.kind == 'buffer' and (m.persistable or not persistable_only):
                    yield path, v

    def buffers(self):
        return [b for _, b in self.named_buffers()]

    def meta_for(self, name) -> '_Meta':
        return self._param_meta.get(name, _META_BUFFER)

    def set_param_meta(self, name, **updates):
        m = self._param_meta.get(name, _META_BUFFER)
        self._param_meta[name] = m._replace(**updates)

    # -- state dict -------------------------------------------------------
    def state_dict(self, destination=None, prefix=''):
        dest = destination if destination is not None else OrderedDict()
        for name, v in self._children():
            path = f"{prefix}.{name}" if prefix else name
            if isinstance(v, Layer):
                v.state_dict(destination=dest, prefix=path)
            else:
                m = self._param_meta.get(name, _META_BUFFER)
                if m.kind == 'param' or m.persistable:
                    if hasattr(v, '_state_dict_entries'):
                        # composite param (e.g. QuantizedWeight): store
                        # its arrays under sub-keys so checkpoints hold
                        # only plain arrays and round-trip by path
                        for sub, arr in v._state_dict_entries():
                            dest[f'{path}.{sub}'] = arr
                    else:
                        dest[path] = v
        return dest

    def set_state_dict(self, state_dict, strict=True):
        missing, own = [], self.state_dict()
        for path in own:
            if path in state_dict:
                self._set_by_path(path, jnp.asarray(state_dict[path]))
            else:
                missing.append(path)
        unexpected = [k for k in state_dict if k not in own]
        if strict and (missing or unexpected):
            raise ValueError(
                f"set_state_dict mismatch: missing={missing} unexpected={unexpected}"
            )
        return missing, unexpected

    load_dict = set_state_dict
    load_state_dict = set_state_dict

    def _set_by_path(self, path, value):
        parts = path.split('.')
        obj = self
        for p in parts[:-1]:
            obj = getattr(obj, p)
        object.__setattr__(obj, parts[-1], value)

    # -- modes ------------------------------------------------------------
    def train(self):
        for l in self.named_sublayers(include_self=True):
            object.__setattr__(l[1], 'training', True)
        return self

    def eval(self):
        for l in self.named_sublayers(include_self=True):
            object.__setattr__(l[1], 'training', False)
        return self

    def apply(self, fn):
        for _, l in self.named_sublayers(include_self=True):
            fn(l)
        return self

    # -- dtype / device ---------------------------------------------------
    def astype(self, dtype, floating_only=True):
        """Cast parameters & buffers in place (ref: Layer.to / amp O2)."""
        dtype = dtype_mod.convert_dtype(dtype)
        for _, l in self.named_sublayers(include_self=True):
            for name, v in list(l._children()):
                if isinstance(v, Layer):
                    continue
                if floating_only and not (
                    jnp.issubdtype(v.dtype, jnp.floating)
                    or v.dtype == jnp.bfloat16
                ):
                    continue
                object.__setattr__(l, name, v.astype(dtype))
        return self

    to = astype

    # -- call -------------------------------------------------------------
    def forward(self, *args, **kwargs):  # pragma: no cover - abstract
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        # dygraph tape: a bound optimizer (parameters=net.parameters())
        # or Variable inputs record the whole call as one vjp node so the
        # canonical loss.backward()/opt.step() loop works (ref: dygraph
        # tracer, python/paddle/base/dygraph/tensor_patch_methods.py).
        # Never records inside jax transforms — tracers mean a functional
        # transform owns this call.
        if self.__dict__.get('_dygraph', False) or _args_may_tape(args, kwargs):
            from ...autograd import eager

            tape, has_var = eager.module_call_would_tape(self, args, kwargs)
            if tape:
                return eager.call_module(self, args, kwargs)
            if has_var:
                args, kwargs = eager.unwrap((args, kwargs))
        return self.forward(*args, **kwargs)

    def __repr__(self):
        n_params = sum(int(np.prod(p.shape)) for p in self.parameters())
        return f"{type(self).__name__}(params={n_params})"

    # -- rng --------------------------------------------------------------
    def _init_rng(self):
        """Give this layer a private PRNG key leaf (threaded functionally)."""
        self.register_buffer('_rng_key', random_mod.split_key(), persistable=False)

    def next_rng_key(self):
        new, key = jax.random.split(self._rng_key)
        object.__setattr__(self, '_rng_key', new)
        return key


_META_BUFFER = _Meta('buffer', False, True, None)

_register(Layer)
