"""Norm layers (ref: python/paddle/nn/layer/norm.py).

BatchNorm keeps running stats as buffer leaves and updates them in-place
on the (possibly traced) layer object — returning the model from a jitted
train step carries the new stats out functionally.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import functional as F
from .. import initializer as I
from .base import Buffer, Layer
from .common import _init_of


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format='NCHW', use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                (num_features,), initializer=_init_of(weight_attr) or I.Constant(1.0)
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter((num_features,), is_bias=True)
        else:
            self.bias = None
        self.register_buffer('_mean', jnp.zeros((num_features,)))
        self.register_buffer('_variance', jnp.ones((num_features,)))

    def forward(self, x):
        training = self.training and not self.use_global_stats
        out, new_mean, new_var = F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=training, momentum=self.momentum, epsilon=self.epsilon,
            data_format=self.data_format,
        )
        if training:
            object.__setattr__(self, '_mean', new_mean)
            object.__setattr__(self, '_variance', new_var)
        return out


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format='NCL', use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format='NCDHW', use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """On TPU under pjit, per-device batch stats are already global when the
    batch axis is sharded and reductions run under GSPMD — XLA inserts the
    cross-replica psum. So SyncBatchNorm == BatchNorm in this framework
    (ref: nn/layer/norm.py::SyncBatchNorm, which wraps NCCL allreduce).
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self.normalized_shape, initializer=_init_of(weight_attr) or I.Constant(1.0)
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(self.normalized_shape, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias, self.epsilon)


class RMSNorm(Layer):
    """ref: paddle.incubate.nn.FusedRMSNorm / Llama RMSNorm."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            self.normalized_shape, initializer=_init_of(weight_attr) or I.Constant(1.0)
        )

    def forward(self, x):
        from ...ops import rms_norm as fused_rms_norm

        return fused_rms_norm(x, self.weight, self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format='NCHW', name=None):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_channels,), initializer=_init_of(weight_attr) or I.Constant(1.0)
        )
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), is_bias=True
        )

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias, self.epsilon, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format='NCHW', name=None):
        super().__init__()
        self.num_features = num_features
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                (num_features,), initializer=_init_of(weight_attr) or I.Constant(1.0)
            )
            self.bias = self.create_parameter((num_features,), is_bias=True)
        else:
            self.weight = None
            self.bias = None

    def forward(self, x):
        return F.instance_norm(x, self.weight, self.bias, self.epsilon, self.data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format='NCHW', name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k, self.data_format)


class SpectralNorm(Layer):
    """Power-iteration spectral norm of a weight (ref: nn/layer/norm.py)."""

    def __init__(self, weight_shape, axis=0, power_iters=1, epsilon=1e-12, dtype='float32'):
        super().__init__()
        self.axis = axis
        self.power_iters = power_iters
        self.epsilon = epsilon
        import numpy as np

        h = weight_shape[axis]
        w = int(np.prod(weight_shape)) // h
        from ...framework import random as random_mod
        import jax

        self.register_buffer('weight_u', jax.random.normal(random_mod.split_key(), (h,)))
        self.register_buffer('weight_v', jax.random.normal(random_mod.split_key(), (w,)))

    def forward(self, weight):
        w_mat = jnp.moveaxis(weight, self.axis, 0).reshape(weight.shape[self.axis], -1)
        u, v = self.weight_u, self.weight_v
        for _ in range(self.power_iters):
            v = w_mat.T @ u
            v = v / (jnp.linalg.norm(v) + self.epsilon)
            u = w_mat @ v
            u = u / (jnp.linalg.norm(u) + self.epsilon)
        object.__setattr__(self, 'weight_u', u)
        object.__setattr__(self, 'weight_v', v)
        sigma = u @ w_mat @ v
        return weight / sigma
