"""Activation layers (ref: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from .base import Layer


def _simple(fn_name, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = {**fixed}
            # positional args map onto the functional's keyword order
            fn = getattr(F, fn_name)
            import inspect

            sig = list(inspect.signature(fn).parameters)[1:]
            for name, v in zip(sig, args):
                self._kwargs[name] = v
            for k, v in kwargs.items():
                if k != 'name':
                    self._kwargs[k] = v

        def forward(self, x):
            return getattr(F, fn_name)(x, **self._kwargs)

    _Act.__name__ = fn_name
    return _Act


ReLU = _simple('relu')
ReLU6 = _simple('relu6')
GELU = _simple('gelu')
SiLU = _simple('silu')
Swish = _simple('swish')
Sigmoid = _simple('sigmoid')
LogSigmoid = _simple('log_sigmoid')
Tanh = _simple('tanh')
Tanhshrink = _simple('tanhshrink')
Softmax = _simple('softmax')
LogSoftmax = _simple('log_softmax')
LeakyReLU = _simple('leaky_relu')
ELU = _simple('elu')
CELU = _simple('celu')
SELU = _simple('selu')
Hardswish = _simple('hardswish')
Hardsigmoid = _simple('hardsigmoid')
Hardtanh = _simple('hardtanh')
Hardshrink = _simple('hardshrink')
Softshrink = _simple('softshrink')
Softplus = _simple('softplus')
Softsign = _simple('softsign')
Mish = _simple('mish')
ThresholdedReLU = _simple('thresholded_relu')
GLU = _simple('glu')
Maxout = _simple('maxout')


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, data_format='NCHW', name=None):
        super().__init__()
        from .. import initializer as I

        self.data_format = data_format
        self.weight = self.create_parameter((num_parameters,), initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)


class RReLU(Layer):
    def __init__(self, lower=1 / 8.0, upper=1 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


Silu = SiLU  # the reference exports both spellings


class Softmax2D(Layer):
    """Softmax over the channel axis of NCHW input
    (ref: nn/layer/activation.py::Softmax2D)."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        if x.ndim not in (3, 4):
            raise ValueError(f'Softmax2D expects 3-D or 4-D input, '
                             f'got {x.ndim}-D')
        return F.softmax(x, axis=-3)
