"""Loss layers (ref: python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from .. import functional as F
from .base import Layer


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction='mean',
                 soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
        super().__init__()
        if weight is not None:
            self.register_buffer('weight', weight)
        else:
            self.weight = None
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(
            input, label, self.weight, self.ignore_index, self.reduction,
            self.soft_label, self.axis, self.use_softmax, self.label_smoothing,
        )


class MSELoss(Layer):
    def __init__(self, reduction='mean'):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction='mean', name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction='mean', delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class HuberLoss(Layer):
    def __init__(self, reduction='mean', delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.huber_loss(input, label, self.delta, self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction='mean', name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction='mean', pos_weight=None, name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.weight, self.reduction, self.pos_weight)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction='mean', name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index, self.reduction)


class KLDivLoss(Layer):
    def __init__(self, reduction='mean', log_target=False):
        super().__init__()
        self.reduction = reduction
        self.log_target = log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction='mean', name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin, self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction='mean', name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction='mean', name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin, self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction='mean', name=None):
        super().__init__()
        self.margin, self.p, self.epsilon, self.swap, self.reduction = margin, p, epsilon, swap, reduction

    def forward(self, anchor, positive, negative):
        return F.triplet_margin_loss(anchor, positive, negative, self.margin, self.p, self.epsilon, self.swap, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction='mean', name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight, self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction='mean', name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8, reduction='mean', name=None):
        super().__init__()
        self.log_input, self.full, self.epsilon, self.reduction = log_input, full, epsilon, reduction

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, self.log_input, self.full, self.epsilon, self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction='mean', name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, self.full, self.epsilon, self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction='mean'):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths, norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths, self.blank, self.reduction, norm_by_times)


class MultiMarginLoss(Layer):
    """ref: nn/layer/loss.py::MultiMarginLoss."""

    def __init__(self, p=1, margin=1.0, weight=None, reduction='mean',
                 name=None):
        super().__init__()
        self.p, self.margin, self.weight = p, margin, weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    """ref: nn/layer/loss.py::TripletMarginWithDistanceLoss."""

    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction='mean', name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin,
            self.swap, self.reduction)


class RNNTLoss(Layer):
    """ref: nn/layer/loss.py::RNNTLoss."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction='mean',
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           self.blank, self.fastemit_lambda, self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid with learned node classifiers
    (ref: nn/layer/loss.py::HSigmoidLoss). Holds the (num_classes-1, D)
    non-leaf weight matrix (custom trees supply per-call paths)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        if not is_custom and num_classes < 2:
            raise ValueError('num_classes must be >= 2 for the default tree')
        self.num_classes = num_classes
        self.is_custom = is_custom
        rows = num_classes if is_custom else num_classes - 1
        self.weight = self.create_parameter((rows, feature_size))
        self.bias = None if bias_attr is False else self.create_parameter(
            (rows, 1), is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        if self.is_custom and (path_table is None or path_code is None):
            raise ValueError('custom tree requires path_table and path_code')
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table, path_code)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Adaptive softmax head (ref: nn/layer/loss.py::
    AdaptiveLogSoftmaxWithLoss): frequent classes scored directly, rare
    classes through down-projected tail clusters (cluster i projects to
    in_features / div_value**(i+1) dims) — O(head) compute for the
    common case instead of O(n_classes)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if (cutoffs != sorted(cutoffs) or min(cutoffs) <= 0
                or max(cutoffs) > n_classes - 1
                or len(set(cutoffs)) != len(cutoffs)):
            raise ValueError('cutoffs must be unique, positive, increasing '
                             'and < n_classes')
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.div_value = div_value
        shortlist = self.cutoffs[0]
        n_clusters = len(self.cutoffs) - 1
        self.head_weight = self.create_parameter(
            (in_features, shortlist + n_clusters))
        self.head_bias = (self.create_parameter(
            (shortlist + n_clusters,), is_bias=True) if head_bias else None)
        # tails live ONLY as registered tail_proj_i/tail_out_i attributes
        # (a plain-list copy would land in the pytree's static aux as
        # unhashable arrays and break treedef equality under jit)
        for i in range(n_clusters):
            hsz = max(1, int(in_features // (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            self.add_parameter(f'tail_proj_{i}',
                               self.create_parameter((in_features, hsz)))
            self.add_parameter(f'tail_out_{i}',
                               self.create_parameter((hsz, osz)))

    def _tails(self):
        # read through the registered attributes so jit/pytree updates
        # (which rebind attributes, not a cached list) are respected
        out = []
        for i in range(len(self.cutoffs) - 1):
            out.append([getattr(self, f'tail_proj_{i}'),
                        getattr(self, f'tail_out_{i}')])
        return out

    @property
    def tail_weights(self):
        """Reference-compatible view of the tail cluster parameters."""
        return self._tails()

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self._tails(), self.cutoffs,
            self.head_bias)

    def log_prob(self, input):
        """Full (N, n_classes) log-probabilities."""
        import jax
        import jax.numpy as jnp

        x = input.astype(jnp.float32)
        head = x @ self.head_weight
        if self.head_bias is not None:
            head = head + self.head_bias
        head_logp = jax.nn.log_softmax(head, axis=-1)
        shortlist = self.cutoffs[0]
        pieces = [head_logp[:, :shortlist]]
        for i, (proj, w_out) in enumerate(self._tails()):
            tail_logp = jax.nn.log_softmax((x @ proj) @ w_out, axis=-1)
            pieces.append(head_logp[:, shortlist + i:shortlist + i + 1]
                          + tail_logp)
        return jnp.concatenate(pieces, axis=-1)

    def predict(self, input):
        import jax.numpy as jnp

        return jnp.argmax(self.log_prob(input), axis=-1)
