"""Transformer layers (ref: python/paddle/nn/layer/transformer.py).

Attention dispatches through F.scaled_dot_product_attention → pallas
flash attention on TPU. Layout (B, S, H, D) throughout; no (B*H) reshape
dance — XLA prefers the 4-D batched matmul form.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import functional as F
from .base import Layer
from .common import Dropout, Linear
from .container import LayerList
from .norm import LayerNorm


class MultiHeadAttention(Layer):
    """ref: paddle.nn.MultiHeadAttention."""

    Cache = tuple

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        if dropout > 0:
            self._init_rng()

    def _split(self, x):
        B, S, _ = x.shape
        return x.reshape(B, S, self.num_heads, self.head_dim)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        q = self._split(self.q_proj(query))
        k = self._split(self.k_proj(key))
        v = self._split(self.v_proj(value))
        if cache is not None:
            pk, pv = cache
            k = jnp.concatenate([pk, k], axis=1)
            v = jnp.concatenate([pv, v], axis=1)
        rng = self.next_rng_key() if (self.dropout > 0 and self.training) else None
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            training=self.training, rng_key=rng,
        )
        B, S = out.shape[:2]
        out = self.out_proj(out.reshape(B, S, self.embed_dim))
        if cache is not None:
            return out, (k, v)
        return out

    def gen_cache(self, key, value=None, type=None):
        B = key.shape[0]
        z = jnp.zeros((B, 0, self.num_heads, self.head_dim), key.dtype)
        return (z, z)


class TransformerEncoderLayer(Layer):
    """ref: paddle.nn.TransformerEncoderLayer."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1, activation='relu',
                 attn_dropout=None, act_dropout=None, normalize_before=False,
                 weight_attr=None, bias_attr=None, layer_norm_eps=1e-5):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout if attn_dropout is None else attn_dropout,
            weight_attr=weight_attr, bias_attr=bias_attr,
        )
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout_act = Dropout(dropout if act_dropout is None else act_dropout)
        self.activation = activation

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, attn_mask=src_mask)
        else:
            src, cache = self.self_attn(src, src, src, attn_mask=src_mask, cache=cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        act = getattr(F, self.activation)
        src = self.linear2(self.dropout_act(act(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([encoder_layer] + [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class TransformerDecoderLayer(Layer):
    """ref: paddle.nn.TransformerDecoderLayer."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1, activation='relu',
                 attn_dropout=None, act_dropout=None, normalize_before=False,
                 weight_attr=None, bias_attr=None, layer_norm_eps=1e-5):
        super().__init__()
        self.normalize_before = normalize_before
        ad = dropout if attn_dropout is None else attn_dropout
        self.self_attn = MultiHeadAttention(d_model, nhead, ad, weight_attr=weight_attr, bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, ad, weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm3 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.dropout_act = Dropout(dropout if act_dropout is None else act_dropout)
        self.activation = activation

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, attn_mask=tgt_mask)
        else:
            tgt, new_cache = self.self_attn(tgt, tgt, tgt, attn_mask=tgt_mask, cache=cache)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, attn_mask=memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        act = getattr(F, self.activation)
        tgt = self.linear2(self.dropout_act(act(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, new_cache)


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([decoder_layer] + [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None):
        out = tgt
        for layer in self.layers:
            out = layer(out, memory, tgt_mask=tgt_mask, memory_mask=memory_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class Transformer(Layer):
    """ref: paddle.nn.Transformer."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6, num_decoder_layers=6,
                 dim_feedforward=2048, dropout=0.1, activation='relu', attn_dropout=None,
                 act_dropout=None, normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        enc = TransformerEncoderLayer(d_model, nhead, dim_feedforward, dropout, activation,
                                      attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
        dec = TransformerDecoderLayer(d_model, nhead, dim_feedforward, dropout, activation,
                                      attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
        norm_e = LayerNorm(d_model) if normalize_before else None
        norm_d = LayerNorm(d_model) if normalize_before else None
        self.encoder = TransformerEncoder(enc, num_encoder_layers, norm_e)
        self.decoder = TransformerDecoder(dec, num_decoder_layers, norm_d)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask, memory_mask=memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        return jnp.tril(jnp.ones((length, length), jnp.bool_))[None, None]
