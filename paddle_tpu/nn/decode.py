"""Cell-based decoding (ref: python/paddle/nn/decode.py —
BeamSearchDecoder:1-700, dynamic_decode:700-1165).

TPU-native redesign: the reference drives a Python while-loop with
dynamic-shaped TensorArrays; here `dynamic_decode` is one `lax.scan`
over a static step count with boolean finished-masking, so the whole
decode compiles to a single XLA program. The decoder contract matches
the reference: `initialize() -> (inputs, states, finished)`,
`step(time, inputs, states) -> (outputs, states, next_inputs, finished)`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


class Decoder:
    """Abstract decoder (ref: decode.py::Decoder)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states


class BeamSearchDecoder(Decoder):
    """Beam search over an RNN-style cell (ref: decode.py::BeamSearchDecoder).

    cell(inputs, states) -> (cell_out, next_states); `output_fn` maps
    cell_out to vocab logits; `embedding_fn` maps token ids to the next
    step's inputs. States are tiled to (batch*beam, ...) internally.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn or (lambda ids: ids)
        self.output_fn = output_fn or (lambda x: x)
        self._neg = -1e9

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """(B, ...) → (B*beam, ...) by repeating each row (ref util)."""
        return jax.tree.map(lambda t: jnp.repeat(t, beam_size, axis=0), x)

    def _split(self, t):
        return t.reshape((-1, self.beam_size) + t.shape[1:])

    def _merge(self, t):
        return t.reshape((-1,) + t.shape[2:])

    def initialize(self, initial_cell_states):
        states = self.tile_beam_merge_with_batch(initial_cell_states,
                                                 self.beam_size)
        bk = jax.tree.leaves(states)[0].shape[0]
        B = bk // self.beam_size
        tok = jnp.full((bk,), self.start_token, jnp.int32)
        # beam 0 live, the rest masked so identical prefixes don't tie
        log_probs = jnp.where(jnp.arange(self.beam_size)[None, :] == 0,
                              0.0, self._neg)
        log_probs = jnp.broadcast_to(log_probs, (B, self.beam_size))
        beam_state = {
            'cell_states': states,
            'log_probs': log_probs,
            'finished': jnp.zeros((B, self.beam_size), bool),
            'lengths': jnp.zeros((B, self.beam_size), jnp.int32),
        }
        finished = beam_state['finished']
        return self.embedding_fn(tok), beam_state, finished

    def step(self, time, inputs, beam_state):
        K = self.beam_size
        cell_out, cell_states = self.cell(inputs, beam_state['cell_states'])
        logits = self.output_fn(cell_out)                # (B*K, V)
        V = logits.shape[-1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        logp = self._split(logp)                         # (B, K, V)
        B = logp.shape[0]

        finished = beam_state['finished']
        frozen = jnp.full((V,), self._neg).at[self.end_token].set(0.0)
        logp = jnp.where(finished[:, :, None], frozen[None, None], logp)
        cand = beam_state['log_probs'][:, :, None] + logp

        top_scores, top_idx = jax.lax.top_k(cand.reshape(B, K * V), K)
        beam_idx = top_idx // V                          # (B, K)
        tok = (top_idx % V).astype(jnp.int32)

        gather = (jnp.arange(B)[:, None] * K + beam_idx).reshape(-1)
        cell_states = jax.tree.map(lambda s: s[gather], cell_states)
        barng = jnp.arange(B)[:, None]
        finished = finished[barng, beam_idx]
        lengths = beam_state['lengths'][barng, beam_idx]
        lengths = jnp.where(finished, lengths, lengths + 1)
        finished = finished | (tok == self.end_token)

        next_state = {
            'cell_states': cell_states,
            'log_probs': top_scores,
            'finished': finished,
            'lengths': lengths,
        }
        outputs = {'token': tok, 'parent': beam_idx,
                   'score': top_scores}
        return outputs, next_state, self.embedding_fn(self._merge(tok)), finished

    def finalize(self, outputs, final_states, sequence_lengths):
        """Backtrack parent pointers → (B, beam, T) token sequences."""
        toks = outputs['token']                          # (T, B, K)
        parents = outputs['parent']
        T, B, K = toks.shape

        def back(carry, t):
            beam = carry                                 # (B, K)
            tok = toks[t][jnp.arange(B)[:, None], beam]
            beam = parents[t][jnp.arange(B)[:, None], beam]
            return beam, tok

        init = jnp.broadcast_to(jnp.arange(K)[None], (B, K))
        _, rev = jax.lax.scan(back, init, jnp.arange(T - 1, -1, -1))
        seqs = jnp.flip(rev, 0).transpose(1, 2, 0)       # (B, K, T)
        return seqs, final_states


def dynamic_decode(decoder, inits=None, max_step_num=32, output_time_major=False,
                   return_length=False, **kwargs):
    """ref: paddle.nn.dynamic_decode — run `decoder` to completion.

    One `lax.scan` over max_step_num steps; steps after all beams finish
    are masked no-ops (XLA-friendly alternative to the reference's early
    exit, same result).
    """
    inputs, states, finished = decoder.initialize(inits)

    def step(carry, t):
        inputs, states, finished = carry
        outputs, next_states, next_inputs, next_finished = decoder.step(
            t, inputs, states)
        # once everything is finished, freeze states (masked no-op step)
        keep = jnp.all(finished)
        next_states = jax.tree.map(
            lambda new, old: jnp.where(keep, old, new), next_states, states)
        next_finished = next_finished | finished
        return (next_inputs, next_states, next_finished), outputs

    (inputs, states, finished), outputs = jax.lax.scan(
        step, (inputs, states, finished), jnp.arange(max_step_num))

    lengths = states['lengths'] if isinstance(states, dict) and \
        'lengths' in states else None
    finalized, states = decoder.finalize(outputs, states, lengths)
    # layout contract (matches the reference): scan stacks time-major;
    # BeamSearchDecoder.finalize backtracks into batch-major (B, K, T)
    if isinstance(decoder, BeamSearchDecoder):
        if output_time_major:
            finalized = jax.tree.map(
                lambda t: jnp.moveaxis(t, -1, 0), finalized)
    elif not output_time_major:
        finalized = jax.tree.map(lambda t: jnp.swapaxes(t, 0, 1), finalized)
    if return_length:
        return finalized, states, lengths
    return finalized, states
