"""Weight normalization (ref: python/paddle/nn/utils/weight_norm_hook.py).

Functional re-parameterisation: the layer stores (v, g) parameters and
recomputes weight = g * v / ||v|| in a pre-forward wrapper.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..layer.base import Parameter


def _norm_except(v, axis):
    if axis is None:
        return jnp.sqrt(jnp.sum(jnp.square(v)))
    axes = tuple(i for i in range(v.ndim) if i != axis)
    return jnp.sqrt(jnp.sum(jnp.square(v), axis=axes, keepdims=True))


def weight_norm(layer, name='weight', dim=0):
    w = getattr(layer, name)
    g = _norm_except(w, dim)
    setattr(layer, name + '_v', Parameter(w))
    setattr(layer, name + '_g', Parameter(g.reshape(-1) if dim is not None else g))
    delattr(layer, name)

    orig_forward = layer.forward

    def forward(*args, **kwargs):
        v = getattr(layer, name + '_v')
        gg = getattr(layer, name + '_g')
        if dim is not None:
            shape = [1] * v.ndim
            shape[dim] = -1
            gg = gg.reshape(shape)
        n = _norm_except(v, dim)
        object.__setattr__(layer, name, v / n * gg)
        out = orig_forward(*args, **kwargs)
        return out

    layer.forward = forward
    layer._weight_norm_name = name
    layer._weight_norm_dim = dim
    return layer


def remove_weight_norm(layer, name='weight'):
    dim = getattr(layer, '_weight_norm_dim', 0)
    v = getattr(layer, name + '_v')
    g = getattr(layer, name + '_g')
    if dim is not None:
        shape = [1] * v.ndim
        shape[dim] = -1
        g = g.reshape(shape)
    n = _norm_except(v, dim)
    setattr(layer, name, Parameter(v / n * g))
    delattr(layer, name + '_v')
    delattr(layer, name + '_g')
    layer.forward = type(layer).forward.__get__(layer)
    return layer
