"""spectral_norm hook (ref: python/paddle/nn/utils/spectral_norm_hook.py)."""
from __future__ import annotations

from ..layer.norm import SpectralNorm


def spectral_norm(layer, name='weight', n_power_iterations=1, eps=1e-12, dim=None):
    w = getattr(layer, name)
    if dim is None:
        dim = 1 if type(layer).__name__.endswith('Transpose') else 0
    sn = SpectralNorm(w.shape, axis=dim, power_iters=n_power_iterations, epsilon=eps)
    layer._spectral_norm = sn
    orig_forward = layer.forward

    def forward(*args, **kwargs):
        object.__setattr__(layer, name, layer._spectral_norm(getattr(layer, name)))
        return orig_forward(*args, **kwargs)

    layer.forward = forward
    return layer
