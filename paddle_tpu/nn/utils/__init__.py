"""nn.utils (ref: python/paddle/nn/utils)."""
from ..clip import clip_grad_norm_, clip_grad_value_  # noqa: F401
from .weight_norm import remove_weight_norm, weight_norm  # noqa: F401
from .spectral_norm import spectral_norm  # noqa: F401


def parameters_to_vector(parameters):
    import jax.numpy as jnp

    return jnp.concatenate([p.reshape(-1) for p in parameters])


def vector_to_parameters(vec, parameters):
    import numpy as np

    out = []
    offset = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        out.append(vec[offset : offset + n].reshape(p.shape))
        offset += n
    return out
