"""Legacy paddle.dataset namespace (ref: python/paddle/dataset) — the
pre-2.0 downloadable dataset helpers. Superseded by vision.datasets /
text / audio.datasets (all download-free here); this shim routes the
commonly-imported names to their modern homes so old scripts import.
"""
from __future__ import annotations


def __getattr__(name):
    routes = {
        'mnist': 'paddle_tpu.vision.datasets (MNIST)',
        'cifar': 'paddle_tpu.vision.datasets (Cifar10/Cifar100)',
        'flowers': 'paddle_tpu.vision.datasets (Flowers)',
        'imdb': 'paddle_tpu.text (Imdb)',
        'imikolov': 'paddle_tpu.text (Imikolov)',
        'uci_housing': 'paddle_tpu.text (UCIHousing)',
        'conll05': 'paddle_tpu.text datasets',
        'movielens': 'paddle_tpu.text datasets',
        'wmt14': 'paddle_tpu.text datasets',
        'wmt16': 'paddle_tpu.text datasets',
    }
    if name in routes:
        raise ImportError(
            f'paddle.dataset.{name} is the deprecated pre-2.0 API; use '
            f'{routes[name]} — same data, Dataset/DataLoader interface')
    raise AttributeError(name)
