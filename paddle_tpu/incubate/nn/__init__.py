"""paddle_tpu.incubate.nn (ref: python/paddle/incubate/nn)."""
from . import functional  # noqa: F401
from .layer import (FusedBiasDropoutResidualLayerNorm,  # noqa: F401
                    FusedDropout, FusedDropoutAdd, FusedFeedForward,
                    FusedLinear, FusedMultiHeadAttention,
                    FusedMultiTransformer, FusedTransformerEncoderLayer)
