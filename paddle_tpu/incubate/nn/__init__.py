"""paddle_tpu.incubate.nn (ref: python/paddle/incubate/nn)."""
from . import functional  # noqa: F401
