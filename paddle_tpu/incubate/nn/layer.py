"""incubate.nn fused Layers (ref: python/paddle/incubate/nn/layer/
fused_transformer.py and fused_linear.py).

The reference classes wrap hand-fused CUDA kernels; here each Layer owns
ordinary pytree Parameters and lowers to the composed-jnp/pallas
functional ops in `incubate.nn.functional` — XLA does the fusing, the
TPU fast paths (flash attention, fused decode) dispatch underneath.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...nn import functional as F  # noqa: F401 (activation lookup)
from ...nn import initializer as I
from ...nn.layer.base import Layer, Parameter
from . import functional as FF

_ACTS = {'gelu': jax.nn.gelu, 'relu': jax.nn.relu, 'silu': jax.nn.silu}


class FusedLinear(Layer):
    """ref: incubate/nn/layer/fused_linear.py::FusedLinear."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        init = I.XavierNormal()
        shape = ((out_features, in_features) if transpose_weight
                 else (in_features, out_features))
        self.weight = Parameter(init(shape, 'float32'))
        self.bias = (None if bias_attr is False
                     else Parameter(jnp.zeros((out_features,), jnp.float32)))
        self._transpose = transpose_weight

    def forward(self, x):
        return FF.fused_matmul_bias(x, self.weight, self.bias,
                                    transpose_y=self._transpose)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """ref: fused_transformer.py:94 — out = LN(residual + dropout(x + b))."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.bias = Parameter(jnp.zeros((embed_dim,), jnp.float32))
        self.ln_scale = Parameter(jnp.ones((embed_dim,), jnp.float32))
        self.ln_bias = Parameter(jnp.zeros((embed_dim,), jnp.float32))

    def forward(self, x, residual):
        h = FF.fused_dropout_add(x + self.bias, residual,
                                 self.dropout_rate,
                                 training=getattr(self, 'training', True))
        return FF.fused_layer_norm(h, self.ln_scale, self.ln_bias,
                                   self.epsilon)


class FusedMultiHeadAttention(Layer):
    """ref: fused_transformer.py:213 — packed-QKV attention block with
    residual + LN (flash fast path on TPU via the functional op)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        if need_weights:
            raise NotImplementedError(
                'need_weights=True is unsupported (the reference raises '
                'too — the fused kernel never materialises probabilities)')
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.epsilon = epsilon
        init = I.XavierNormal()
        self.qkv_weight = Parameter(
            init((3, num_heads, self.head_dim, embed_dim), 'float32'))
        self.qkv_bias = Parameter(
            jnp.zeros((3 * embed_dim,), jnp.float32))
        self.linear_weight = Parameter(init((embed_dim, embed_dim),
                                            'float32'))
        self.linear_bias = Parameter(jnp.zeros((embed_dim,), jnp.float32))
        self.pre_ln_scale = Parameter(jnp.ones((embed_dim,), jnp.float32))
        self.pre_ln_bias = Parameter(jnp.zeros((embed_dim,), jnp.float32))
        self.ln_scale = Parameter(jnp.ones((embed_dim,), jnp.float32))
        self.ln_bias = Parameter(jnp.zeros((embed_dim,), jnp.float32))

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        if (key is not None and key is not query) or (
                value is not None and value is not query):
            raise NotImplementedError(
                'cross-attention is unsupported: the reference fused op '
                'is self-attention only (query==key==value)')
        out = FF.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self.epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, cache_kv=cache,
            attn_mask=attn_mask, dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            ln_epsilon=self.epsilon,
            training=getattr(self, 'training', True),
            num_heads=self.num_heads)
        return out


class FusedFeedForward(Layer):
    """ref: fused_transformer.py:534 — LN + linear + act + linear +
    residual."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation='relu', act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        init = I.XavierNormal()
        self.normalize_before = normalize_before
        self.activation = activation
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (act_dropout_rate
                                 if act_dropout_rate is not None
                                 else dropout_rate)
        self.epsilon = epsilon
        self.linear1_weight = Parameter(init((d_model, dim_feedforward),
                                             'float32'))
        self.linear1_bias = Parameter(jnp.zeros((dim_feedforward,),
                                                jnp.float32))
        self.linear2_weight = Parameter(init((dim_feedforward, d_model),
                                             'float32'))
        self.linear2_bias = Parameter(jnp.zeros((d_model,), jnp.float32))
        self.ln1_scale = Parameter(jnp.ones((d_model,), jnp.float32))
        self.ln1_bias = Parameter(jnp.zeros((d_model,), jnp.float32))
        self.ln2_scale = Parameter(jnp.ones((d_model,), jnp.float32))
        self.ln2_bias = Parameter(jnp.zeros((d_model,), jnp.float32))

    def forward(self, src):
        return FF.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias, linear2_bias=self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            dropout1_rate=self.act_dropout_rate,
            dropout2_rate=self.dropout_rate,
            activation=self.activation, ln1_epsilon=self.epsilon,
            ln2_epsilon=self.epsilon,
            pre_layer_norm=self.normalize_before,
            training=getattr(self, 'training', True))


class FusedTransformerEncoderLayer(Layer):
    """ref: fused_transformer.py:750 — FusedMultiHeadAttention +
    FusedFeedForward."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation='relu', attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False, **kw):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(attn_dropout_rate
                               if attn_dropout_rate is not None
                               else dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        if cache is not None:
            raise NotImplementedError(
                'incremental cache on the encoder layer is unsupported; '
                'use FusedMultiTransformer for generation')
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class FusedMultiTransformer(Layer):
    """ref: fused_transformer.py:1071 — the serving-side decoder stack:
    N pre/post-LN self-attention + FFN layers sharing one API, with
    per-layer contiguous KV caches (the masked_multihead_attention
    (2, B, H, max_seq, D) layout) and `time_step` single-token decode.

    TPU-native: prefill runs the flash-attention path and writes the
    caches; decode steps route through
    functional.masked_multihead_attention (head-major fused kernel).
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation='gelu',
                 normalize_before=True, num_layers=-1, nranks=1,
                 trans_qkvw=True, ring_id=-1, name=None, epsilon=1e-5,
                 **_attr_kw):
        super().__init__()
        if num_layers < 1:
            raise ValueError('num_layers must be >= 1 (attr-list '
                             'construction is not supported; pass '
                             'num_layers explicitly)')
        if not trans_qkvw:
            raise NotImplementedError(
                'trans_qkvw=False (untransposed qkv weights) unsupported')
        if activation not in _ACTS:
            raise ValueError(f'activation must be one of {list(_ACTS)}')
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.activation = activation
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.num_layers = num_layers
        init = I.XavierNormal()
        H, D, E = num_heads, self.head_dim, embed_dim

        def plist(make):
            from ...nn import LayerList

            class _P(Layer):
                def __init__(self):
                    super().__init__()
                    self.w = Parameter(make())

            return LayerList([_P() for _ in range(num_layers)])

        self.ln_scales = plist(lambda: jnp.ones((E,), jnp.float32))
        self.ln_biases = plist(lambda: jnp.zeros((E,), jnp.float32))
        # reference trans_qkvw layout: (3, num_head, head_dim, embed_dim)
        self.qkv_weights = plist(lambda: init((3, H, D, E), 'float32'))
        self.qkv_biases = plist(lambda: jnp.zeros((3 * E,), jnp.float32))
        self.linear_weights = plist(lambda: init((E, E), 'float32'))
        self.linear_biases = plist(lambda: jnp.zeros((E,), jnp.float32))
        self.ffn_ln_scales = plist(lambda: jnp.ones((E,), jnp.float32))
        self.ffn_ln_biases = plist(lambda: jnp.zeros((E,), jnp.float32))
        self.ffn1_weights = plist(
            lambda: init((E, dim_feedforward), 'float32'))
        self.ffn1_biases = plist(
            lambda: jnp.zeros((dim_feedforward,), jnp.float32))
        self.ffn2_weights = plist(
            lambda: init((dim_feedforward, E), 'float32'))
        self.ffn2_biases = plist(lambda: jnp.zeros((E,), jnp.float32))

    def gen_cache(self, batch_size, max_seq_len, dtype=jnp.float32):
        """Per-layer (2, B, H, max_seq, D) zero caches (the reference's
        cache_kvs layout)."""
        shape = (2, batch_size, self.num_heads, max_seq_len, self.head_dim)
        return [jnp.zeros(shape, dtype) for _ in range(self.num_layers)]

    def _layer(self, i, x, attn_mask, cache, time_step, seq_lens):
        from ...nn.functional.attention import scaled_dot_product_attention
        from ...nn.functional.norm import layer_norm

        E, H, D = self.embed_dim, self.num_heads, self.head_dim
        residual = x
        h = layer_norm(x, E, self.ln_scales[i].w, self.ln_biases[i].w,
                       self.epsilon) if self.normalize_before else x
        qkv_w = self.qkv_weights[i].w                   # (3, H, D, E)
        new_cache = cache
        if time_step is not None:
            # single-token decode: fused head-major kernel over the
            # contiguous cache
            xt = h[:, 0]                                 # (B, E)
            qkv_flat = jnp.einsum('be,thde->bthd', xt, qkv_w).reshape(
                xt.shape[0], 3 * E) + self.qkv_biases[i].w
            lens = (seq_lens if seq_lens is not None
                    else jnp.full((x.shape[0], 1), time_step, jnp.int32))
            attn_out, new_cache = FF.masked_multihead_attention(
                qkv_flat, cache_kv=cache, sequence_lengths=lens)
            attn_out = attn_out[:, None]                 # (B, 1, E)
        else:
            qkv = jnp.einsum('bse,thde->bsthd', h, qkv_w)
            qkv = qkv + self.qkv_biases[i].w.reshape(3, H, D)[None, None]
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            attn_out = scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, is_causal=attn_mask is None)
            attn_out = attn_out.reshape(*h.shape[:2], E)
            if cache is not None:                        # prefill writes
                S = h.shape[1]
                new_cache = cache.at[0, :, :, :S].set(
                    jnp.swapaxes(k, 1, 2).astype(cache.dtype))
                new_cache = new_cache.at[1, :, :, :S].set(
                    jnp.swapaxes(v, 1, 2).astype(cache.dtype))
        attn_out = attn_out @ self.linear_weights[i].w \
            + self.linear_biases[i].w
        x = FF.fused_dropout_add(
            attn_out, residual, self.dropout_rate,
            training=getattr(self, 'training', True))
        if not self.normalize_before:
            x = layer_norm(x, E, self.ln_scales[i].w, self.ln_biases[i].w,
                           self.epsilon)

        residual = x
        h = layer_norm(x, E, self.ffn_ln_scales[i].w,
                       self.ffn_ln_biases[i].w, self.epsilon) \
            if self.normalize_before else x
        h = _ACTS[self.activation](h @ self.ffn1_weights[i].w
                                   + self.ffn1_biases[i].w)
        h = h @ self.ffn2_weights[i].w + self.ffn2_biases[i].w
        # reference parity: the FFN output is dropped out into the
        # residual too (fused_multi_transformer post-process)
        x = FF.fused_dropout_add(h, residual, self.dropout_rate,
                                 training=getattr(self, 'training', True))
        if not self.normalize_before:
            x = layer_norm(x, E, self.ffn_ln_scales[i].w,
                           self.ffn_ln_biases[i].w, self.epsilon)
        return x, new_cache

    def forward(self, src, attn_mask=None, caches=None, pre_caches=None,
                rotary_embs=None, rotary_emb_dims=0, beam_offset=None,
                seq_lens=None, time_step=None):
        if pre_caches is not None or beam_offset is not None:
            raise NotImplementedError(
                'pre_caches / beam_offset belong to the reference CUDA '
                'serving pipeline and are not supported')
        if rotary_embs is not None:
            raise NotImplementedError(
                'rotary_embs: rotate q/k outside or use the Llama family '
                'models for RoPE serving')
        if time_step is not None and src.shape[1] != 1:
            raise ValueError('time_step decode expects a single token '
                             f'per row, got seq {src.shape[1]}')
        if time_step is not None and attn_mask is not None:
            raise NotImplementedError(
                'attn_mask is not applied on time_step decode steps '
                '(the cache window is positional) — drive padded decode '
                'via seq_lens instead of a mask')
        x = src
        new_caches = [] if caches is not None else None
        for i in range(self.num_layers):
            cache = caches[i] if caches is not None else None
            x, nc = self._layer(i, x, attn_mask, cache, time_step,
                                seq_lens)
            if new_caches is not None:
                new_caches.append(nc)
        if caches is not None:
            return x, new_caches
        return x


class FusedDropoutAdd(Layer):
    """ref: incubate/nn/layer/fused_dropout_add.py — dropout(x) + y."""

    def __init__(self, p=0.5, mode='upscale_in_train', name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return FF.fused_dropout_add(x, y, self.p,
                                    training=getattr(self, 'training', True),
                                    mode=self.mode)

    def extra_repr(self):
        return f'p={self.p}, mode={self.mode}'


class FusedDropout(Layer):
    """ref: incubate/nn/layer/fused_dropout_nd.py — plain dropout with
    an optional axis (dropout_nd broadcast pattern)."""

    def __init__(self, p=0.5, axis=None, mode='upscale_in_train',
                 name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis,
                         training=getattr(self, 'training', True),
                         mode=self.mode)
