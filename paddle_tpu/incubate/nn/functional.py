"""Fused ops (ref: python/paddle/incubate/nn/functional/*).

The reference hand-fuses these into single CUDA kernels; on TPU the
same fusion happens in XLA, so each "fused_*" here is the composed jnp
expression (single dispatch under jit) routed through the pallas fast
paths where one exists (rms_norm, flash attention).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False):
    """ref: incubate/nn/functional/fused_matmul_bias.py."""
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    out = x @ y
    return out if bias is None else out + bias


fused_linear = fused_matmul_bias


def swiglu(x, y=None):
    """ref: incubate/nn/functional/swiglu.py — silu(x) * y; single-arg
    form splits the last dim in half."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


def _flatten_norm(x, begin_norm_axis):
    """Paddle norm semantics: normalize over ALL trailing axes from
    begin_norm_axis; returns (flattened x, restore shape) — a no-op view
    for the default last-axis case."""
    axis = begin_norm_axis % x.ndim if begin_norm_axis >= 0 else \
        x.ndim + begin_norm_axis
    if axis == x.ndim - 1:
        return x, None
    shape = x.shape
    return x.reshape(shape[:axis] + (-1,)), shape


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kw):
    """ref: fused_rms_norm.py — dispatches to the pallas kernel on TPU."""
    from ...ops import rms_norm as _rms

    xf, shape = _flatten_norm(x, begin_norm_axis)
    out = _rms(xf, norm_weight.reshape(-1) if norm_weight is not None
               else None, epsilon)
    if norm_bias is not None:
        out = out + norm_bias.reshape(-1)
    return out if shape is None else out.reshape(shape)


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, residual=None, **kw):
    """ref: fused_layer_norm.py (residual-add + LN)."""
    from ...nn.functional.norm import layer_norm

    if residual is not None:
        x = x + residual
    xf, shape = _flatten_norm(x, begin_norm_axis)
    out = layer_norm(xf, xf.shape[-1],
                     norm_weight.reshape(-1) if norm_weight is not None
                     else None,
                     norm_bias.reshape(-1) if norm_bias is not None
                     else None, epsilon)
    return out if shape is None else out.reshape(shape)


def fused_dropout_add(x, y, p=0.0, training=True, mode='upscale_in_train',
                      rng_key=None):
    """ref: fused_dropout_add.py — dropout(x) + y."""
    if p == 0.0:
        return x + y
    if not training:
        # downscale_in_infer: train keeps raw activations, infer scales
        if mode == 'downscale_in_infer':
            x = x * (1 - p)
        return x + y
    from ...framework import random as random_mod

    key = rng_key if rng_key is not None else random_mod.split_key()
    keep = jax.random.bernoulli(key, 1 - p, x.shape)
    if mode == 'upscale_in_train':
        x = jnp.where(keep, x / (1 - p), 0.0)
    else:
        x = jnp.where(keep, x, 0.0)
    return x + y


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True):
    """ref: fused_rotary_position_embedding.py.

    q/k/v: (B, S, H, D). When sin/cos are None they are computed from
    positions with the default 10000 theta. Accepts the reference's
    full-head-dim cos/sin layout ((1, S, 1, D), both halves duplicated)
    or the compact (S, D/2)/(B, S, D/2) tables. use_neox_rotary_style
    selects rotate-half (True) vs GPT-J interleaved pairs (False).
    Returns rotated (q, k, v) — v passes through (rope only mixes q/k,
    the reference accepts it for API parity).
    """
    from ...models.llama import apply_rotary, rope_cos_sin

    B, S, _, D = q.shape
    if cos is None or sin is None:
        if position_ids is None:
            position_ids = jnp.broadcast_to(jnp.arange(S)[None], (B, S))
        cos, sin = rope_cos_sin(position_ids, D, dtype=q.dtype)
    else:
        def canon(t):
            t = jnp.asarray(t)
            if t.ndim == 4:                # reference layout (B|1, S, 1, D)
                t = t[:, :, 0, :]
            if t.ndim == 2:                # (S, Dx) → (1, S, Dx)
                t = t[None]
            if t.shape[-1] == D:
                # full-head-dim table: halves duplicated (neox) or
                # pairwise-duplicated (interleaved)
                t = t[..., ::2] if not use_neox_rotary_style else \
                    t[..., :D // 2]
            if position_ids is not None:
                # gather table rows at the requested positions (decode
                # steps pass the full-length table + position_ids=[[t]])
                t = jnp.broadcast_to(t, (B,) + t.shape[1:])
                t = jnp.take_along_axis(
                    t, jnp.asarray(position_ids)[:, :, None], axis=1)
            return jnp.broadcast_to(t, (B, S, D // 2))

        cos, sin = canon(cos), canon(sin)

    if use_neox_rotary_style:
        rot = lambda x: apply_rotary(x, cos, sin)
    else:
        # GPT-J style: rotate adjacent pairs (2i, 2i+1)
        c = cos[:, :, None, :]
        s = sin[:, :, None, :]

        def rot(x):
            xp = x.reshape(*x.shape[:-1], D // 2, 2)
            xe, xo = xp[..., 0], xp[..., 1]
            re = xe * c - xo * s
            ro = xo * c + xe * s
            return jnp.stack([re, ro], -1).reshape(x.shape).astype(x.dtype)

    out_q = rot(q)
    out_k = rot(k) if k is not None else None
    return out_q, out_k, v


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None,
                               ln_scale=None, ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               training=True, num_heads=None):
    """ref: fused_transformer.py::fused_multi_head_attention — packed-QKV
    self-attention block with residual + layer norm, flash-attention fast
    path on TPU.

    x: (B, S, E); qkv_weight: (3, num_heads, head_dim, E) (reference
    layout); linear_weight: (E, E).
    """
    from ...nn.functional.attention import scaled_dot_product_attention
    from ...nn.functional.norm import layer_norm

    B, S, E = x.shape
    three, H, D, _ = qkv_weight.shape
    assert three == 3 and H * D == E

    residual = x
    if pre_layer_norm:
        x = layer_norm(x, E, pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    qkv = jnp.einsum('bse,thde->bsthd', x, qkv_weight)     # (B,S,3,H,D)
    if qkv_bias is not None:
        qkv = qkv + qkv_bias.reshape(3, H, D)[None, None]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]     # (B,S,H,D)
    new_cache = None
    if cache_kv is not None:
        # ref layout (2, B, H, S_past, D): append, attend over the
        # full prefix, and return the grown cache alongside the output
        past_k = jnp.swapaxes(cache_kv[0], 1, 2)           # (B,S_past,H,D)
        past_v = jnp.swapaxes(cache_kv[1], 1, 2)
        k = jnp.concatenate([past_k, k], axis=1)
        v = jnp.concatenate([past_v, v], axis=1)
        new_cache = jnp.stack([jnp.swapaxes(k, 1, 2),
                               jnp.swapaxes(v, 1, 2)])
    out = scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate,
        training=training)
    out = out.reshape(B, S, E) @ linear_weight
    if linear_bias is not None:
        out = out + linear_bias
    if dropout_rate:
        out = fused_dropout_add(out, residual, dropout_rate, training)
    else:
        out = out + residual
    if not pre_layer_norm:
        out = layer_norm(out, E, ln_scale, ln_bias, ln_epsilon)
    if new_cache is not None:
        return out, new_cache
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation='relu',
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True):
    """ref: fused_transformer.py::fused_feedforward — LN + MLP + residual."""
    from ...nn.functional.norm import layer_norm

    E = x.shape[-1]
    residual = x
    if pre_layer_norm:
        x = layer_norm(x, E, ln1_scale, ln1_bias, ln1_epsilon)
    act = {'relu': jax.nn.relu, 'gelu': jax.nn.gelu,
           'silu': jax.nn.silu}[activation]
    h = act(fused_matmul_bias(x, linear1_weight, linear1_bias))
    if dropout1_rate and training:
        h = fused_dropout_add(h, jnp.zeros_like(h), dropout1_rate, training)
    h = fused_matmul_bias(h, linear2_weight, linear2_bias)
    out = fused_dropout_add(h, residual, dropout2_rate, training) \
        if dropout2_rate and training else h + residual
    if not pre_layer_norm:
        out = layer_norm(out, E, ln2_scale, ln2_bias, ln2_epsilon)
    return out


def fused_bias_act(x, bias=None, act_method='gelu'):
    """ref: fused_bias_act.py."""
    if bias is not None:
        x = x + bias
    return {'gelu': jax.nn.gelu, 'relu': jax.nn.relu, 'silu': jax.nn.silu,
            'swiglu': swiglu}[act_method](x)
